"""Multi-tenant QoS tests: priority classes, preemption, SLO shedding.

The QoS contracts (docs/SERVING.md QoS section), each pinned here on
CPU with the tiny model:

* **priority-ordered admission** — with contention, a queued
  ``interactive`` request admits before an earlier-queued ``batch`` one;
* **preempt → park → re-admit byte parity** — a batch request preempted
  mid-decode by an interactive burst (DLREQ01 park, pages freed) resumes
  and finishes byte-identical to its uncontended solo run, with the
  two-deep overlapped dispatch pipeline both on and off, and the pool
  ends with zero leaked pages;
* **starvation bound** — ``--preempt-age-ms`` ages a waiting request's
  effective level so batch eventually beats fresh interactive arrivals;
* **bounded preemption** — ``--preempt-cap`` / parked-area exhaustion
  retire the victim with the honest ``finish_reason="preempted"``
  instead of parking it forever;
* **SLO shed order** — under a burning fast window only ``batch`` is
  shed (429 + jittered Retry-After); a full ``violating`` verdict sheds
  ``standard`` too; ``interactive`` is never shed;
* **router scoring** — an SLO-violating replica is penalized for
  batch/standard dispatch but stays fully scored for interactive;
* **exposition** — the three new metric families surface in both
  /metrics formats, and flight records carry priority / preempt_count.
"""

import json
import random
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from fixtures import free_port, write_tiny_tokenizer

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.obs import metrics as obs_metrics
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime.engine import Engine
from dllama_tpu.runtime.faults import FAULTS
from dllama_tpu.runtime.scheduler import (PRIORITY_LEVELS, PRIORITY_NAMES,
                                          SlotScheduler)
from dllama_tpu.server.backoff import JITTER_FRAC, jittered_retry_after

pytestmark = pytest.mark.qos

CFG = tiny_config(seq_len=64)
PAGE = 4
P1 = [5, 9, 2]
P2 = [7, 3, 11, 4, 6, 1, 8]
P3 = [2, 4, 6]


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


def make_engine(batch=1):
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                  batch=batch)


def make_paged_engine(batch=2, page=PAGE):
    pages_per_slot = -(-CFG.seq_len // page)
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                  batch=batch,
                  kv_pages=batch * pages_per_slot + 1,
                  kv_page_size=page)


@pytest.fixture(scope="module")
def solo_refs():
    """Greedy solo completions per prompt — the parity oracle."""
    eng = make_engine()
    refs = {}
    for p in (P1, P2, P3):
        eng.reset()
        toks = [t for t, _ in eng.generate_stream(
            p, len(p) + 30, temperature=0.0, chunk=5)]
        refs[tuple(p)] = toks[len(p):]
    return refs


# --- unit: priority parsing + Retry-After jitter --------------------------

def test_priority_level_parsing():
    from dllama_tpu.server.api import priority_level
    assert priority_level("interactive") == 0
    assert priority_level("Standard") == 1
    assert priority_level(" BATCH ") == 2
    assert priority_level("turbo") is None
    assert priority_level(None) is None
    assert priority_level(3) is None
    assert PRIORITY_NAMES[PRIORITY_LEVELS["batch"]] == "batch"


def test_retry_after_jitter_bounds():
    rng = random.Random(7)
    draws = {int(jittered_retry_after(8, rng)) for _ in range(500)}
    lo, hi = 8 * (1 - JITTER_FRAC), 8 * (1 + JITTER_FRAC)
    assert min(draws) >= int(lo) and max(draws) <= round(hi)
    assert len(draws) > 1, "jitter must actually spread the hint"
    # floor: tiny/zero/garbage bases still return a valid >=1s hint
    for bad in (0, -3, "0.2", None, "soon"):
        assert int(jittered_retry_after(bad, rng)) >= 1


def test_shed_order_unit():
    """Interactive never sheds; batch sheds on a burning fast window;
    standard only on a full violating verdict."""
    from dllama_tpu.server.api import ApiState

    class Shim:  # just enough of ApiState for should_shed
        def __init__(self, verdict):
            self.slo = type("S", (), {
                "evaluate": staticmethod(lambda v=verdict: v)})()

    burning = {"status": "ok", "windows": ["30s", "5m"],
               "objectives": {"ttft_p95": {"burn": {"30s": 1.4, "5m": 0.2}}}}
    violating = {"status": "violating", "windows": ["30s", "5m"],
                 "objectives": {"ttft_p95": {"burn": {"30s": 2.0,
                                                      "5m": 1.1}}}}
    calm = {"status": "ok", "windows": ["30s", "5m"],
            "objectives": {"ttft_p95": {"burn": {"30s": 0.1, "5m": 0.0}}}}
    shed = ApiState.should_shed
    for lvl in PRIORITY_LEVELS.values():
        assert not shed(Shim(calm), lvl)
    assert shed(Shim(burning), PRIORITY_LEVELS["batch"])
    assert not shed(Shim(burning), PRIORITY_LEVELS["standard"])
    assert not shed(Shim(burning), PRIORITY_LEVELS["interactive"])
    assert shed(Shim(violating), PRIORITY_LEVELS["batch"])
    assert shed(Shim(violating), PRIORITY_LEVELS["standard"])
    assert not shed(Shim(violating), PRIORITY_LEVELS["interactive"])


def test_router_score_keeps_violating_replica_for_interactive():
    from dllama_tpu.router.registry import Backend, Registry
    reg = Registry(["127.0.0.1:1", "127.0.0.1:2"], probe_interval=3600)
    burning, calm = reg.backends
    burning.last_health = {"status": "serving", "slo": {"status":
                                                        "violating"},
                           "capacity": {"free_slots": 4, "queue_depth": 0}}
    calm.last_health = {"status": "serving", "slo": {"status": "ok"},
                        "capacity": {"free_slots": 1, "queue_depth": 0}}
    # low-priority dispatch avoids the burning replica...
    assert reg.pick() is calm
    assert reg.pick(priority="batch") is calm
    # ...but interactive sees its real (larger) capacity
    assert reg.pick(priority="interactive") is burning
    # degraded kernels penalize EVERY class — only the SLO penalty is
    # priority-conditional
    burning.last_health["degraded"] = True
    assert reg.pick(priority="interactive") is calm


# --- unit: metric exposition (both formats) -------------------------------

def test_qos_metrics_in_both_formats():
    obs_metrics.SCHED_PREEMPTIONS.inc("no_free_slot")
    obs_metrics.SCHED_PREEMPT_PARKED.set(2)
    obs_metrics.ADMISSIONS_SHED.inc("batch")
    snap = obs_metrics.snapshot_json()
    assert snap["sched_preemptions"]["no_free_slot"] >= 1
    assert snap["sched_preempt_parked"] == 2
    assert snap["admissions_shed"]["batch"] >= 1
    text = obs_metrics.render_prometheus()
    assert 'dllama_sched_preemptions_total{reason="no_free_slot"}' in text
    assert "dllama_sched_preempt_parked" in text
    assert 'dllama_admissions_shed_total{class="batch"}' in text
    obs_metrics.SCHED_PREEMPT_PARKED.set(0)


# --- scheduler: ordering, aging, preemption -------------------------------

def test_priority_ordered_admission(solo_refs):
    """One slot, no preemption: a later-queued interactive request
    admits (and therefore finishes) before an earlier-queued batch one."""
    eng = make_engine(1)
    sched = SlotScheduler(eng, prefill_chunk=4, decode_burst=4,
                          preempt=False, preempt_age_ms=0.0)
    try:
        done: dict = {}

        def run(key, prompt, prio):
            t = sched.submit(prompt, 8, priority=prio)
            toks = list(t.tokens())
            done[key] = (time.monotonic(), toks, t.finish)

        hold = sched.submit(P2, 12)  # occupies the only slot
        b = threading.Thread(target=run,
                             args=("batch", P1, PRIORITY_LEVELS["batch"]))
        b.start()
        time.sleep(0.15)  # batch is queued first, beyond doubt
        i = threading.Thread(
            target=run, args=("inter", P3, PRIORITY_LEVELS["interactive"]))
        i.start()
        list(hold.tokens())
        b.join(120)
        i.join(120)
        assert done["inter"][0] < done["batch"][0], \
            "interactive must be admitted before the earlier batch request"
        assert done["inter"][1] == solo_refs[tuple(P3)][:8]
        assert done["batch"][1] == solo_refs[tuple(P1)][:8]
    finally:
        sched.close()


def test_aging_bounds_starvation(solo_refs):
    """A batch request that has waited past --preempt-age-ms outranks a
    fresh interactive arrival: starvation is bounded by aging."""
    eng = make_engine(1)
    # 60ms per aging step: after ~150ms a batch request (level 2) has
    # aged to level 0 and ties break by arrival time (it is older)
    sched = SlotScheduler(eng, prefill_chunk=4, decode_burst=4,
                          preempt=False, preempt_age_ms=60.0)
    try:
        done: dict = {}

        def run(key, prompt, prio):
            t = sched.submit(prompt, 8, priority=prio)
            toks = list(t.tokens())
            done[key] = (time.monotonic(), toks)

        hold = sched.submit(P2, 12)
        b = threading.Thread(target=run,
                             args=("batch", P1, PRIORITY_LEVELS["batch"]))
        b.start()
        time.sleep(0.3)  # > 2×2 aging steps: batch is at level <= 0 now
        i = threading.Thread(
            target=run, args=("inter", P3, PRIORITY_LEVELS["interactive"]))
        i.start()
        list(hold.tokens())
        b.join(120)
        i.join(120)
        assert done["batch"][0] < done["inter"][0], \
            "an aged batch request must not starve behind fresh interactive"
        assert done["batch"][1] == solo_refs[tuple(P1)][:8]
        assert done["inter"][1] == solo_refs[tuple(P3)][:8]
    finally:
        sched.close()


@pytest.mark.parametrize("overlap", [True, False],
                         ids=["overlap", "no-overlap"])
def test_preempt_park_resume_byte_parity(solo_refs, overlap):
    """THE preemption acceptance: an interactive burst lands while every
    slot decodes batch work → one batch slot is preempted (DLREQ01 park,
    pages freed), the interactive request serves, and the victim resumes
    to a byte-identical finish — with the overlapped dispatch pipeline
    both on and off, and zero pages leaked at the end."""
    eng = make_paged_engine(batch=2)
    # prefix_reuse off: the end-state page audit must be exact (the
    # radix cache legitimately retains prefix pages otherwise)
    sched = SlotScheduler(eng, prefill_chunk=4, decode_burst=4,
                          overlap=overlap, preempt=True,
                          preempt_age_ms=0.0, prefix_reuse=False)
    base = obs_metrics.snapshot_json().get("sched_preemptions") or {}
    try:
        done: dict = {}

        def run(key, prompt, n, prio):
            t = sched.submit(prompt, n, priority=prio)
            done[key] = (list(t.tokens()), t.finish, t.preempt_count)

        # slow decode keeps both batch requests on device long enough
        FAULTS.install("engine.device_step=delay:0.05x1000")
        b1 = threading.Thread(target=run, args=(
            "b1", P1, 30, PRIORITY_LEVELS["batch"]))
        b2 = threading.Thread(target=run, args=(
            "b2", P2, 30, PRIORITY_LEVELS["batch"]))
        b1.start()
        b2.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sched.occupancy()["active"] == 2:
                break
            time.sleep(0.01)
        else:
            raise AssertionError("batch never saturated the slots")
        time.sleep(0.3)  # both are mid-decode, bursts in flight
        it = threading.Thread(target=run, args=(
            "it", P3, 6, PRIORITY_LEVELS["interactive"]))
        it.start()
        it.join(120)
        FAULTS.clear()
        b1.join(240)
        b2.join(240)

        assert done["it"][0] == solo_refs[tuple(P3)][:6]
        assert done["it"][1] == "length"
        pre = obs_metrics.snapshot_json().get("sched_preemptions") or {}
        bumped = sum(pre.values()) - sum(base.values())
        assert bumped >= 1, "interactive must have preempted a batch slot"
        victims = [k for k in ("b1", "b2") if done[k][2] >= 1]
        assert victims, f"no ticket recorded a preemption: {done}"
        for k, p in (("b1", P1), ("b2", P2)):
            toks, finish, _ = done[k]
            assert finish == "length", (k, finish)
            assert toks == solo_refs[tuple(p)][:30], \
                f"{k} drifted after resume"
        occ = sched.occupancy()
        assert occ["active"] == 0 and occ["parked"] == 0, occ
        assert occ["kv_pages_free"] == occ["kv_pages_total"], \
            f"page leak: {occ}"
        sched.pool.check()  # raises on any refcount/free-list violation
    finally:
        FAULTS.clear()
        sched.close()


def test_preempt_spilled_slot_resume_byte_parity(solo_refs):
    """Preemption meets KV tiering: on an optimistic over-committed pool
    a batch slot may be SPILLED (pages in host RAM, not resident) when
    the interactive burst preempts it.  The park exporter must read the
    victim's KV from the host pool, drop its spill record, and the
    resumed request must still finish byte-identical — with the host
    pool drained and zero pages leaked at the end."""
    pages_per_slot = -(-CFG.seq_len // PAGE)
    eng = Engine(CFG, init_params(CFG, seed=4),
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                 batch=2, kv_pages=11, kv_page_size=PAGE)
    assert 11 - 1 < 2 * pages_per_slot, "pool must be over-committed"
    sched = SlotScheduler(eng, prefill_chunk=4, decode_burst=4,
                          preempt=True, preempt_age_ms=0.0,
                          prefix_reuse=False, kv_reserve="optimistic",
                          spill_headroom=4, host_pool_mb=8)
    spilled0 = obs_metrics.KV_PAGES_SPILLED.value
    try:
        done: dict = {}

        def run(key, prompt, n, prio):
            t = sched.submit(prompt, n, priority=prio)
            done[key] = (list(t.tokens()), t.finish)

        FAULTS.install("engine.device_step=delay:0.05x1000")
        b1 = threading.Thread(target=run, args=(
            "b1", P1, 30, PRIORITY_LEVELS["batch"]))
        b2 = threading.Thread(target=run, args=(
            "b2", P2, 30, PRIORITY_LEVELS["batch"]))
        b1.start()
        b2.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sched.occupancy()["active"] == 2:
                break
            time.sleep(0.01)
        else:
            raise AssertionError("batch never saturated the slots")
        # let both grow past their bindings: the 10-usable-page pool
        # cannot hold 2 × ~9-page demand, so spill traffic is running
        # when the interactive burst lands
        time.sleep(0.5)
        it = threading.Thread(target=run, args=(
            "it", P3, 6, PRIORITY_LEVELS["interactive"]))
        it.start()
        it.join(120)
        FAULTS.clear()
        b1.join(240)
        b2.join(240)

        assert obs_metrics.KV_PAGES_SPILLED.value - spilled0 >= 1, \
            "over-committed pool never spilled"
        assert done["it"][0] == solo_refs[tuple(P3)][:6]
        for k, p in (("b1", P1), ("b2", P2)):
            toks, finish = done[k]
            assert finish == "length", (k, finish)
            assert toks == solo_refs[tuple(p)][:30], \
                f"{k} drifted through spill/park/resume"
        occ = sched.occupancy()
        assert occ["active"] == 0 and occ["parked"] == 0, occ
        assert occ["kv_pages_free"] == occ["kv_pages_total"], \
            f"page leak: {occ}"
        assert occ["kv_pressure"]["host_pool_bytes"] == 0, occ
        assert occ["kv_pressure"]["spilled_slots"] == 0, occ
        sched.pool.check()
    finally:
        FAULTS.clear()
        sched.close()


def test_preempt_cap_retires_with_honest_finish():
    """preempt_cap=0: the victim cannot be parked, so preemption retires
    it with finish_reason="preempted" and its partial output intact."""
    eng = make_paged_engine(batch=1)
    sched = SlotScheduler(eng, prefill_chunk=4, decode_burst=2,
                          preempt=True, preempt_cap=0, preempt_age_ms=0.0)
    try:
        FAULTS.install("engine.device_step=delay:0.05x1000")
        victim = sched.submit(P2, 30, priority=PRIORITY_LEVELS["batch"])
        got: list = []
        t = threading.Thread(target=lambda: got.extend(victim.tokens()))
        t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sched.occupancy()["active"] == 1:
                break
            time.sleep(0.01)
        time.sleep(0.2)
        inter = sched.submit(P3, 4, priority=PRIORITY_LEVELS["interactive"])
        out = list(inter.tokens())
        FAULTS.clear()
        t.join(120)
        assert victim.finish == "preempted", victim.finish
        assert len(got) < 30, "victim must have been cut short"
        assert len(out) == 4 and inter.finish == "length"
        occ = sched.occupancy()
        assert occ["parked"] == 0 and \
            occ["kv_pages_free"] == occ["kv_pages_total"], occ
    finally:
        FAULTS.clear()
        sched.close()


def test_parked_area_full_retires_with_honest_finish():
    """parked_max=0: nowhere to park → same honest "preempted" finish."""
    eng = make_paged_engine(batch=1)
    sched = SlotScheduler(eng, prefill_chunk=4, decode_burst=2,
                          preempt=True, parked_max=0, preempt_age_ms=0.0)
    try:
        FAULTS.install("engine.device_step=delay:0.05x1000")
        victim = sched.submit(P2, 30, priority=PRIORITY_LEVELS["batch"])
        t = threading.Thread(target=lambda: list(victim.tokens()))
        t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sched.occupancy()["active"] == 1:
                break
            time.sleep(0.01)
        time.sleep(0.2)
        inter = sched.submit(P3, 4, priority=PRIORITY_LEVELS["interactive"])
        assert len(list(inter.tokens())) == 4
        FAULTS.clear()
        t.join(120)
        assert victim.finish == "preempted", victim.finish
    finally:
        FAULTS.clear()
        sched.close()


@pytest.mark.slow
def test_preempt_spill_dir_roundtrip(solo_refs, tmp_path):
    """--preempt-spill-dir: the parked DLREQ01 record round-trips through
    disk and the resume is still byte-identical."""
    eng = make_paged_engine(batch=1)
    sched = SlotScheduler(eng, prefill_chunk=4, decode_burst=2,
                          preempt=True, preempt_age_ms=0.0,
                          spill_dir=str(tmp_path))
    try:
        FAULTS.install("engine.device_step=delay:0.05x1000")
        victim = sched.submit(P2, 30, priority=PRIORITY_LEVELS["batch"])
        got: list = []
        t = threading.Thread(target=lambda: got.extend(victim.tokens()))
        t.start()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sched.occupancy()["active"] == 1:
                break
            time.sleep(0.01)
        time.sleep(0.2)
        inter = sched.submit(P3, 4, priority=PRIORITY_LEVELS["interactive"])
        spilled = []
        while inter.finish is None:
            spilled.extend(str(p) for p in tmp_path.glob("*.dlreq"))
            time.sleep(0.01)
        list(inter.tokens())
        FAULTS.clear()
        t.join(240)
        assert spilled, "the parked record must have hit the spill dir"
        assert victim.finish == "length"
        assert got == solo_refs[tuple(P2)][:30], "resume drift after spill"
        assert not list(tmp_path.glob("*.dlreq")), "spill file must be " \
            "cleaned up after resume"
    finally:
        FAULTS.clear()
        sched.close()


# --- live in-process server: API surface + shedding -----------------------

class FakeSlo:
    """Stands in for obs.slo.SloEngine: evaluate() returns whatever
    verdict the test has loaded."""

    def __init__(self):
        self.verdict = {"status": "ok", "windows": ["30s", "5m"],
                        "objectives": {}}

    def observe_ttft(self, *a, **k):
        pass

    def observe_itl(self, *a, **k):
        pass

    def evaluate(self):
        return self.verdict

    def burn(self, fast, slow=0.0):
        self.verdict = {
            "status": "violating" if slow >= 1.0 else "ok",
            "windows": ["30s", "5m"],
            "objectives": {"ttft_p95": {"burn": {"30s": fast,
                                                 "5m": slow}}}}


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from dllama_tpu.tokenizer.bpe import Tokenizer
    d = tmp_path_factory.mktemp("qos")
    tok = Tokenizer(write_tiny_tokenizer(str(d / "tok.t")))
    cfg = tiny_config(seq_len=128, vocab_size=300)
    eng = Engine(cfg, init_params(cfg, seed=4),
                 mesh=make_mesh(tp=1, devices=jax.devices()[:1]), batch=2)
    return eng, tok


@pytest.fixture
def api(stack):
    from dllama_tpu.server.api import ApiState, serve
    servers = []

    def make(**kw):
        eng, tok = stack
        state = ApiState(eng, tok, default_temperature=0.0, chunk=2,
                         batch_engine=eng, **kw)
        srv = serve(state, host="127.0.0.1", port=free_port(), block=False)
        servers.append(srv)
        return state, f"http://127.0.0.1:{srv.server_address[1]}"

    yield make
    for s in servers:
        s.shutdown()
        s.server_close()


def post(base, path, body, headers=None):
    req = urllib.request.Request(
        base + path, json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    return urllib.request.urlopen(req, timeout=120)


def test_shed_order_live(api):
    """End-to-end shed order through the HTTP surface: batch sheds on a
    burning fast window (429 + Retry-After + admissions_shed metric),
    standard follows only on a violating verdict, interactive never."""
    slo = FakeSlo()
    _, base = api(slo=slo)
    body = {"prompt": "hello", "max_tokens": 2}

    with post(base, "/v1/completions", dict(body, priority="batch")) as r:
        assert r.status == 200  # calm SLO: nothing sheds

    slo.burn(fast=1.5)  # fast window burning, slow window fine
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(base, "/v1/completions", dict(body, priority="batch"))
    assert ei.value.code == 429
    assert int(ei.value.headers["Retry-After"]) >= 1
    # the header route sheds identically (router-propagated class)
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(base, "/v1/completions", body,
             headers={"X-Dllama-Priority": "batch"})
    assert ei.value.code == 429
    with post(base, "/v1/completions",
              dict(body, priority="standard")) as r:
        assert r.status == 200
    # the chat surface honors the same class field
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(base, "/v1/chat/completions",
             {"messages": [{"role": "user", "content": "hi"}],
              "max_tokens": 2, "priority": "batch"})
    assert ei.value.code == 429


def test_shed_order_live_violating(api):
    slo = FakeSlo()
    _, base = api(slo=slo)
    body = {"prompt": "hello", "max_tokens": 2}
    slo.burn(fast=2.0, slow=1.2)  # full violating verdict
    for cls in ("batch", "standard"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            post(base, "/v1/completions", dict(body, priority=cls))
        assert ei.value.code == 429, cls
    with post(base, "/v1/completions",
              dict(body, priority="interactive")) as r:
        data = json.loads(r.read())
        assert data["choices"][0]["finish_reason"] in ("stop", "length")
    shed = obs_metrics.snapshot_json().get("admissions_shed") or {}
    assert shed.get("batch", 0) >= 1 and shed.get("standard", 0) >= 1
    assert "interactive" not in shed


def test_unknown_priority_body_is_400(api):
    _, base = api()
    with pytest.raises(urllib.error.HTTPError) as ei:
        post(base, "/v1/completions",
             {"prompt": "x", "max_tokens": 2, "priority": "turbo"})
    assert ei.value.code == 400
    assert "unknown priority class" in ei.value.read().decode()
    # an unknown HEADER (router forwards what it saw) degrades to the
    # default class instead of erroring
    with post(base, "/v1/completions", {"prompt": "x", "max_tokens": 2},
              headers={"X-Dllama-Priority": "turbo"}) as r:
        assert r.status == 200


def test_flight_record_carries_priority(api):
    _, base = api()
    with post(base, "/v1/completions",
              {"prompt": "hello", "max_tokens": 2,
               "priority": "interactive"}) as r:
        rid = r.headers.get("X-Request-Id")
        assert rid
    with urllib.request.urlopen(base + f"/debug/requests/{rid}",
                                timeout=30) as r:
        rec = json.loads(r.read())
    assert rec["priority"] == "interactive"
    with urllib.request.urlopen(base + "/debug/requests?n=5",
                                timeout=30) as r:
        rows = json.loads(r.read())["requests"]
    mine = [x for x in rows if x["request_id"] == rid]
    assert mine and mine[0]["priority"] == "interactive"
    assert "preempt_count" in mine[0]


# --- trace replay harness (tools/trace_replay.py) -------------------------

def test_trace_replay_units():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import trace_replay as tr
    mix = tr.parse_mix("interactive=1,standard=2,batch=1")
    assert [name for name, _ in mix] == ["interactive", "standard", "batch"]
    assert mix[-1][1] == pytest.approx(1.0)
    with pytest.raises(ValueError):
        tr.parse_mix("turbo=1")
    rng = random.Random(3)
    names = {tr._assign(mix, rng) for _ in range(200)}
    assert names == {"interactive", "standard", "batch"}
    t1 = tr.synth_trace(16, 4.0, seed=9)
    t2 = tr.synth_trace(16, 4.0, seed=9)
    assert t1 == t2, "synthetic traces must be reproducible"
    assert len(t1["requests"]) == 16
    offs = [r["offset_s"] for r in t1["requests"]]
    assert offs == sorted(offs) and offs[0] == 0.0
    assert tr._pct([1.0, 2.0, 3.0, 4.0], 0.5) == 3.0
    assert tr._pct([], 0.95) is None
