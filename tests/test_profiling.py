"""Profiler-derived compute/collective split (SURVEY §5-tracing parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.runtime.profiling import profiled_split


def test_profiled_split_sees_collectives():
    """A tp-sharded matmul's all-reduce must show up as collective time."""
    pytest.importorskip("tensorflow")
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from jax.sharding import NamedSharding, PartitionSpec as P
    from dllama_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(tp=8)
    w = jax.device_put(jnp.ones((512, 512)), NamedSharding(mesh, P("tp", None)))
    x = jax.device_put(jnp.ones((8, 512)), NamedSharding(mesh, P(None, "tp")))
    f = jax.jit(lambda x, w: x @ w)
    f(x, w).block_until_ready()  # compile outside the trace

    split = profiled_split(lambda: f(x, w).block_until_ready(), steps=3)
    assert split is not None
    assert split["collective_ms"] > 0, "all-reduce missing from the trace"
    assert split["compute_ms"] > 0
    assert 0 < split["collective_pct"] < 100


def test_profiled_split_engine_decode_step():
    """The CLI --profile-split path: a real engine decode step traces."""
    pytest.importorskip("tensorflow")
    from dllama_tpu.models.config import tiny_config
    from dllama_tpu.models.params import init_params
    from dllama_tpu.runtime.engine import Engine

    cfg = tiny_config(dim=64, hidden_dim=96, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=128, seq_len=32)
    eng = Engine(cfg, init_params(cfg, 0))
    eng.prefill([1, 2, 3])
    split = profiled_split(lambda: eng.decode_one(5), steps=2)
    # a single-device CPU decode has no collectives but must trace cleanly
    assert split is not None
    assert split["compute_ms"] > 0
    assert np.isfinite(split["collective_pct"])
