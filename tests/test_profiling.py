"""Profiler-derived compute/collective split (SURVEY §5-tracing parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.runtime.profiling import profiled_split


def test_profiled_split_sees_collectives():
    """A tp-sharded matmul's all-reduce must show up as collective time."""
    pytest.importorskip("tensorflow")
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from jax.sharding import NamedSharding, PartitionSpec as P
    from dllama_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(tp=8)
    w = jax.device_put(jnp.ones((512, 512)), NamedSharding(mesh, P("tp", None)))
    x = jax.device_put(jnp.ones((8, 512)), NamedSharding(mesh, P(None, "tp")))
    f = jax.jit(lambda x, w: x @ w)
    f(x, w).block_until_ready()  # compile outside the trace

    split = profiled_split(lambda: f(x, w).block_until_ready(), steps=3)
    assert split is not None
    assert split["collective_ms"] > 0, "all-reduce missing from the trace"
    assert split["compute_ms"] > 0
    assert 0 < split["collective_pct"] < 100


def test_profiled_split_engine_decode_step():
    """The CLI --profile-split path: a real engine decode step traces."""
    pytest.importorskip("tensorflow")
    from dllama_tpu.models.config import tiny_config
    from dllama_tpu.models.params import init_params
    from dllama_tpu.runtime.engine import Engine

    cfg = tiny_config(dim=64, hidden_dim=96, n_layers=2, n_heads=4,
                      n_kv_heads=2, vocab_size=128, seq_len=32)
    eng = Engine(cfg, init_params(cfg, 0))
    eng.prefill([1, 2, 3])
    split = profiled_split(lambda: eng.decode_one(5), steps=2)
    # a single-device CPU decode has no collectives but must trace cleanly
    assert split is not None
    assert split["compute_ms"] > 0
    assert np.isfinite(split["collective_pct"])


def test_tpu_style_xplane_parsing(tmp_path):
    """TPU device planes record full HLO instruction strings on an
    'XLA Ops' line, with whole-program and async duplicates on sibling
    lines and nested control-flow spans — parsing must take exactly the
    per-op leaf events (this is what the round-end bench's I/T split and
    per-op profile read; a real trace of this shape can only be produced
    on hardware, so the proto is synthesized here)."""
    pytest.importorskip("tensorflow")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    from dllama_tpu.runtime.profiling import _parse_xspace, op_times

    xs = xplane_pb2.XSpace()
    plane = xs.planes.add(name="/device:TPU:0")
    events = {
        1: ("jit_step(123456)", 100.0),                       # XLA Modules
        2: ("%fusion.3 = f32[32,1024]{1,0:T(8,128)} fusion(f32[...] %a), "
            "kind=kLoop, calls=%fused", 3.0),
        3: ("%all-reduce.1 = f32[1,4096]{1,0} all-reduce(f32[...] %b)", 2.0),
        4: ("%while.32 = (s32[], f32[1,16]) while(...)", 95.0),  # wrapper
        5: ("%copy-start = (f32[2,2]) copy-start(f32[2,2] %c)", 0.5),
    }
    for mid, (name, _) in events.items():
        plane.event_metadata[mid].id = mid
        plane.event_metadata[mid].name = name

    def add_line(name, mids):
        line = plane.lines.add(name=name)
        for mid in mids:
            ev = line.events.add()
            ev.metadata_id = mid
            ev.duration_ps = int(events[mid][1] * 1e9)

    add_line("XLA Modules", [1])          # must be ignored (would double-book)
    add_line("XLA Ops", [2, 3, 4, 5])     # the per-op stream
    add_line("Async XLA Ops", [5])        # subset duplicate, must be ignored

    path = tmp_path / "vm.xplane.pb"
    path.write_bytes(xs.SerializeToString())

    compute_ms, collective_ms = _parse_xspace(str(path))
    # leaves only: fusion 3.0 + copy-start 0.5 compute, all-reduce 2.0
    # collective; the module event and the while wrapper are excluded
    assert compute_ms == pytest.approx(3.5)
    assert collective_ms == pytest.approx(2.0)
    times = op_times(str(tmp_path))
    assert times == {"fusion.3": pytest.approx(3.0),
                     "all-reduce.1": pytest.approx(2.0),
                     "copy-start": pytest.approx(0.5)}
