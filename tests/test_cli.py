"""CLI subprocess tests: the four dllama modes driven end-to-end on tiny
fixture models (reference modes: dllama.cpp:221-252)."""

import pytest

from fixtures import run_cli, write_tiny_model, write_tiny_tokenizer


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("cli")
    m = d / "tiny.m"
    t = d / "tiny.t"
    write_tiny_model(m)
    write_tiny_tokenizer(t)
    return str(m), str(t)


def test_inference_mode_prints_stats(model_files):
    m, t = model_files
    r = run_cli(["inference", "--model", m, "--tokenizer", t,
                 "--prompt", "hello", "--steps", "8", "--temperature", "0"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert "Avg tokens / second:" in r.stdout
    assert "Avg generation time:" in r.stdout
    assert "🔶 G" in r.stdout
    assert "💡 arch: llama" in r.stdout


def test_generate_mode_streams_text(model_files):
    m, t = model_files
    r = run_cli(["generate", "--model", m, "--tokenizer", t,
                 "--prompt", "hello", "--steps", "10", "--temperature", "0", "--seed", "1"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert len(r.stdout.strip()) > 0


def test_generate_deterministic_greedy(model_files):
    m, t = model_files
    args = ["generate", "--model", m, "--tokenizer", t, "--prompt", "hello",
            "--steps", "10", "--temperature", "0"]
    a, b = run_cli(args), run_cli(args)
    assert a.stdout == b.stdout


def test_generate_requires_prompt(model_files):
    m, t = model_files
    r = run_cli(["generate", "--model", m, "--tokenizer", t])
    assert r.returncode != 0
    assert "--prompt" in r.stderr


def test_batch_mode_distinct_streams(model_files, tmp_path):
    """`dllama batch --prompts-file` decodes each line as its own stream
    (beyond reference: tasks.cpp:199-210 is batch=1) and the output is
    deterministic under greedy decoding."""
    m, t = model_files
    pf = tmp_path / "prompts.txt"
    pf.write_text("hello there\nonce upon a time\n")
    args = ["batch", "--model", m, "--tokenizer", t, "--prompts-file", str(pf),
            "--steps", "12", "--temperature", "0"]
    r = run_cli(args)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "▶ stream 0" in r.stdout and "▶ stream 1" in r.stdout
    assert "Batched throughput:" in r.stdout

    def text_only(out):  # drop the wall-clock throughput line
        return [l for l in out.splitlines() if "throughput" not in l]

    assert text_only(run_cli(args).stdout) == text_only(r.stdout)  # greedy determinism


def test_generate_pld_matches_plain_greedy(model_files):
    """--pld must print exactly the vanilla greedy text (speculation only
    changes how many positions one dispatch verifies)."""
    m, t = model_files
    base = ["generate", "--model", m, "--tokenizer", t, "--prompt", "hello",
            "--steps", "24", "--temperature", "0"]
    plain = run_cli(base)
    pld = run_cli(base + ["--pld", "5"])
    assert pld.returncode == 0, pld.stderr[-2000:]
    assert pld.stdout == plain.stdout


def test_batch_mode_requires_prompts(model_files):
    m, t = model_files
    r = run_cli(["batch", "--model", m, "--tokenizer", t])
    assert r.returncode != 0
    assert "--prompts-file" in r.stderr


def test_chat_mode_one_turn(model_files):
    m, t = model_files
    r = run_cli(["chat", "--model", m, "--tokenizer", t, "--temperature", "0",
                 "--steps", "16"], input_text="sys prompt\nhello\n")
    assert r.returncode == 0, r.stderr[-2000:]
    assert "🤖 Assistant" in r.stdout


def test_worker_mode_explains_mapping(model_files):
    r = run_cli(["worker"])
    assert r.returncode == 0
    assert "tpu:N" in r.stdout


def test_missing_model_flag_errors():
    r = run_cli(["inference"])
    assert r.returncode != 0
    assert "--model" in r.stderr


def test_tp4_workers_flag(model_files):
    m, t = model_files
    # nKvHeads=2 caps tp at 2 (reference constraint) — tpu:2 must work
    r = run_cli(["generate", "--model", m, "--tokenizer", t, "--prompt", "hello",
                 "--steps", "6", "--temperature", "0", "--workers", "tpu:2"],
                n_devices=2)
    assert r.returncode == 0, r.stderr[-2000:]
    # and tpu:4 must refuse with the reference's nKvHeads error
    r4 = run_cli(["generate", "--model", m, "--tokenizer", t, "--prompt", "hello",
                  "--steps", "6", "--workers", "tpu:4"], n_devices=4)
    assert r4.returncode != 0
    assert "nKvHeads" in r4.stderr


def test_sp_flag_runs_sequence_parallel(model_files):
    """Long context is operator-reachable: --sp 2 builds a tp×sp mesh from
    the CLI and inference still produces stats (VERDICT r02 Missing #4)."""
    m, t = model_files
    r = run_cli(["inference", "--model", m, "--tokenizer", t, "--prompt", "hello",
                 "--steps", "6", "--temperature", "0", "--workers", "tpu:2",
                 "--sp", "2", "--max-seq-len", "64"], n_devices=4)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "sp=2" in r.stdout and "tp=2" in r.stdout
    assert "Avg tokens / second:" in r.stdout


def test_dp_flag_runs_batched(model_files):
    m, t = model_files
    r = run_cli(["generate", "--model", m, "--tokenizer", t, "--prompt", "hello",
                 "--steps", "6", "--temperature", "0", "--workers", "tpu:2",
                 "--dp", "2"], n_devices=4)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "dp=2" in r.stdout


def test_worker_joins_single_process_group(model_files):
    """Multi-host wiring end-to-end at nproc=1: worker mode initializes the
    JAX process group via the coordinator and runs the mirrored program
    (reference contract: worker executes the same task list as root,
    tasks.cpp:230-256)."""
    m, t = model_files
    r = run_cli(["worker", "--coordinator", "127.0.0.1:39171", "--nproc", "1",
                 "--proc-id", "0", "--program", "generate", "--model", m,
                 "--tokenizer", t, "--prompt", "hello", "--steps", "6",
                 "--temperature", "0"])
    assert r.returncode == 0, r.stderr[-2000:]
    assert len(r.stdout.strip()) > 0
