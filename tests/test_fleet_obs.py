"""Fleet observability tests: metrics federation, cross-replica trace
stitching, and the pod event journal (docs/OBSERVABILITY.md "Fleet
observability").

The acceptance contracts pinned here:

* **event journal** (obs/events.py) — bounded monotonically-sequenced
  ring, ``?since=`` cursor semantics, optional JSONL persistence, and
  the ``dllama_pod_events_total`` counter riding every emit;
* **trace context** (obs/trace.py) — ``X-Dllama-Trace`` ids sanitize
  like request ids, attach to spans via the rid→trace map or the
  ambient contextvar, and export through ``raw()`` with the paired
  ``(perf_now, wall_now)`` clock sample federation needs;
* **federation** (router/fleet.py) — one scrape of the router/pod
  returns every replica's families under a ``replica`` label in both
  expositions, failures marked (``fleet_replica_up 0`` + stale JSON)
  and never silently dropped, pre-existing ``replica`` labels renamed
  ``exported_replica`` instead of duplicated;
* **trace stitching** — spans from two replica processes land on one
  wall-clock-aligned Perfetto timeline under one trace id, with event-
  journal instant markers laid over them;
* **DLREQ01 carriage** — a hand-off export/import and a preempt-park-
  resume both keep the request's trace id end to end, narrated by
  ``handoff``/``preempt``/``resume`` journal events;
* **tools** — ``fleet_top --once`` renders the per-replica table and
  event tail; ``trace_dump --fleet`` writes the stitched file and
  reports which traces crossed replicas.
"""

import http.server
import importlib.util
import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from dllama_tpu.obs import events as obs_events, metrics as obs_metrics, \
    trace as obs_trace
from dllama_tpu.obs.events import EventJournal
from dllama_tpu.router.fleet import (FleetScraper, merge_prometheus,
                                     parse_prometheus)
from dllama_tpu.router.registry import Registry

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- unit: event journal --------------------------------------------------

def test_event_journal_seq_cursor_and_ring_bound():
    j = EventJournal(capacity=4)
    for i in range(6):
        ev = j.emit("spawn", replica=f"r{i}", skipped=None)
        assert "skipped" not in ev          # None fields dropped
        assert ev["seq"] == i + 1
    snap = j.snapshot()
    assert [e["seq"] for e in snap["events"]] == [3, 4, 5, 6]
    assert snap["next_seq"] == 6 and snap["oldest_seq"] == 3
    assert snap["capacity"] == 4
    # cursor: only events after `since`, and an up-to-date cursor is empty
    assert [e["seq"] for e in j.snapshot(4)["events"]] == [5, 6]
    assert j.snapshot(6)["events"] == []
    # ts is wall-clock, ordered with seq
    evs = snap["events"]
    assert all(abs(e["ts"] - time.time()) < 60 for e in evs)


def test_event_journal_jsonl_persistence_and_counter(tmp_path):
    before = (obs_metrics.snapshot_json().get("pod_events") or {})
    j = EventJournal(capacity=8)
    log = tmp_path / "events.jsonl"
    j.set_log_path(str(log))
    j.emit("death", replica="127.0.0.1:1", reason="sigkill")
    j.emit("respawn", replica="127.0.0.1:1", pid=42)
    j.set_log_path(None)
    lines = [json.loads(x) for x in log.read_text().splitlines()]
    assert [e["kind"] for e in lines] == ["death", "respawn"]
    assert lines[0]["reason"] == "sigkill" and lines[1]["pid"] == 42
    # append mode: a restart extends the file
    j2 = EventJournal(capacity=8)
    j2.set_log_path(str(log))
    j2.emit("readmit", replica="127.0.0.1:1")
    j2.set_log_path(None)
    assert len(log.read_text().splitlines()) == 3
    after = (obs_metrics.snapshot_json().get("pod_events") or {})
    for kind in ("death", "respawn", "readmit"):
        assert after.get(kind, 0) >= before.get(kind, 0) + 1


def test_event_journal_module_globals():
    base = obs_events.snapshot()["next_seq"]
    obs_events.emit("scale", direction="up", reason="test")
    snap = obs_events.snapshot(base)
    assert len(snap["events"]) == 1
    assert snap["events"][0]["kind"] == "scale"
    assert "scale" in obs_events.KINDS


# --- unit: trace context --------------------------------------------------

def test_trace_id_sanitize_and_rid_map():
    assert obs_trace.sanitize_trace_id(None) is None
    assert obs_trace.sanitize_trace_id("") is None
    assert obs_trace.sanitize_trace_id("<script>!!") == "script"
    assert obs_trace.sanitize_trace_id("ab" * 100) == "ab" * 32
    tid = obs_trace.new_trace_id()
    assert len(tid) == 32 and obs_trace.sanitize_trace_id(tid) == tid
    obs_trace.set_trace("rid-x", tid)
    assert obs_trace.trace_of("rid-x") == tid
    assert obs_trace.trace_of("rid-unknown") is None
    assert obs_trace.trace_of(None) is None


def test_tracer_raw_cursor_clock_sample_and_span_trace():
    obs_trace.clear()
    tid = obs_trace.new_trace_id()
    obs_trace.set_trace("rid-a", tid)
    t0 = time.perf_counter()
    obs_trace.record("one", t0, t0 + 0.01, rid="rid-a")
    obs_trace.record("two", t0 + 0.02, t0 + 0.03, rid="rid-nomap")
    dump = obs_trace.raw()
    spans = {s["name"]: s for s in dump["spans"]}
    assert spans["one"]["trace"] == tid          # via rid→trace map
    assert spans["two"]["trace"] is None
    # the paired clock sample that federation aligns timelines with
    assert abs(dump["perf_now"] - time.perf_counter()) < 5.0
    assert abs(dump["wall_now"] - time.time()) < 5.0
    # since-cursor: only newer spans
    cur = dump["next_seq"]
    obs_trace.record("three", t0 + 0.04, t0 + 0.05, rid="rid-a")
    inc = obs_trace.raw(cur)
    assert [s["name"] for s in inc["spans"]] == ["three"]
    assert obs_trace.raw(inc["next_seq"])["spans"] == []
    # ambient contextvar fallback when rid has no mapping
    tok = obs_trace.trace_id_var.set("ambient1")
    try:
        obs_trace.record("four", t0, t0 + 0.01, rid="rid-ambient")
    finally:
        obs_trace.trace_id_var.reset(tok)
    four = [s for s in obs_trace.raw()["spans"] if s["name"] == "four"][0]
    assert four["trace"] == "ambient1"
    # the Chrome export surfaces the id for Perfetto queries
    ev = [e for e in obs_trace.trace_json()["traceEvents"]
          if e.get("ph") == "X" and e["name"] == "three"][0]
    assert ev["args"]["trace_id"] == tid
    obs_trace.clear()


# --- unit: prometheus federation merge ------------------------------------

REPLICA_TEXT = """\
# HELP dllama_requests_served_total Requests completed successfully.
# TYPE dllama_requests_served_total counter
dllama_requests_served_total 7
# HELP dllama_ttft_seconds TTFT.
# TYPE dllama_ttft_seconds histogram
dllama_ttft_seconds_bucket{le="0.1"} 3
dllama_ttft_seconds_bucket{le="+Inf"} 7
dllama_ttft_seconds_sum 1.5
dllama_ttft_seconds_count 7
"""

ROUTER_TEXT = """\
# HELP dllama_fleet_replica_up Reachability.
# TYPE dllama_fleet_replica_up gauge
dllama_fleet_replica_up{replica="127.0.0.1:9"} 1
dllama_requests_served_total 1
"""


def test_parse_prometheus_families_and_orphans():
    fams = parse_prometheus(REPLICA_TEXT)
    assert fams["dllama_requests_served_total"]["type"] == "counter"
    hist = fams["dllama_ttft_seconds"]
    assert len(hist["samples"]) == 4     # buckets/sum/count own family
    orphan = parse_prometheus("lonely_metric 3\n")["lonely_metric"]
    assert orphan["type"] is None and orphan["samples"]


def test_merge_prometheus_injects_and_renames_replica_label():
    text = merge_prometheus([("router", ROUTER_TEXT),
                             ("127.0.0.1:1234", REPLICA_TEXT)])
    assert 'dllama_requests_served_total{replica="127.0.0.1:1234"} 7' \
        in text
    assert 'dllama_ttft_seconds_bucket{replica="127.0.0.1:1234",' \
           'le="0.1"} 3' in text
    # the router's own replica-labeled family federates as
    # exported_replica — never a duplicated label (invalid exposition)
    assert 'dllama_fleet_replica_up{replica="router",' \
           'exported_replica="127.0.0.1:9"} 1' in text
    for line in text.splitlines():
        if line and not line.startswith("#"):
            assert line.count('replica="') - \
                line.count('exported_replica="') == 1, line
    # HELP/TYPE once per family even with two sources
    assert text.count("# TYPE dllama_requests_served_total") == 1


# --- integration: fake replicas behind a FleetScraper ---------------------

class _Replica:
    """In-thread HTTP server speaking the replica observability surface
    from canned (settable) documents."""

    def __init__(self, metrics_json=None, prom_text=None,
                 trace_doc=None, events_doc=None):
        self.metrics_json = metrics_json or {"requests_served": 1}
        self.prom_text = prom_text or REPLICA_TEXT
        self.trace_doc = trace_doc
        self.events_doc = events_doc or {"events": [], "next_seq": 0,
                                         "oldest_seq": 1, "capacity": 16}
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.startswith("/metrics"):
                    if "prometheus" in self.path:
                        body, ctype = outer.prom_text.encode(), "text/plain"
                    else:
                        body = json.dumps(outer.metrics_json).encode()
                        ctype = "application/json"
                elif self.path.startswith("/debug/trace"):
                    body = json.dumps(outer.trace_doc or {}).encode()
                    ctype = "application/json"
                elif self.path.startswith("/debug/events"):
                    body = json.dumps(outer.events_doc).encode()
                    ctype = "application/json"
                elif self.path.startswith("/health"):
                    body, ctype = b"{}", "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    @property
    def addr(self):
        return f"127.0.0.1:{self.port}"

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def test_federated_metrics_marks_never_drops():
    r1 = _Replica(metrics_json={"requests_served": 3})
    r2 = _Replica(metrics_json={"requests_served": 5})
    try:
        reg = Registry([r1.addr, r2.addr])
        fs = FleetScraper(reg, timeout=2.0)

        # ONE scrape carries replica-labeled families from BOTH replicas
        # plus the router's own, under distinct replica labels
        text = fs.federated_prometheus()
        for addr in (r1.addr, r2.addr):
            assert f'dllama_requests_served_total{{replica="{addr}"}} ' \
                in text
            assert f'dllama_fleet_replica_up{{replica="router",' \
                   f'exported_replica="{addr}"}} 1' in text

        doc = fs.federated_json()
        assert doc["scope"] == "fleet"
        assert doc["replicas"][r1.addr]["up"] is True
        assert doc["replicas"][r1.addr]["metrics"]["requests_served"] == 3
        assert doc["replicas"][r2.addr]["metrics"]["requests_served"] == 5
        assert "uptime_s" in doc["router"]

        # kill one replica: marked down + stale last-good, never dropped
        r2.close()
        doc = fs.federated_json()
        dead = doc["replicas"][r2.addr]
        assert dead["up"] is False and dead["stale"] is True
        assert dead["metrics"]["requests_served"] == 5
        assert dead["stale_age_s"] >= 0
        text = fs.federated_prometheus()
        assert f'dllama_fleet_replica_up{{replica="router",' \
               f'exported_replica="{r2.addr}"}} 0' in text
        assert (obs_metrics.snapshot_json()["fleet_scrape_errors"]
                .get(r2.addr, 0)) >= 1
    finally:
        r1.close()
        r2.close()


def _trace_doc(spans):
    """A replica ``raw()`` export whose perf clock is an arbitrary epoch
    far from wall time — the stitcher must align on wall_now-perf_now."""
    return {"spans": spans, "next_seq": len(spans), "capacity": 512,
            "perf_now": 1000.0, "wall_now": time.time()}


def _span(name, ts, rid, trace, seq, tid=7):
    return {"name": name, "ts": ts, "dur": 0.01, "tid": tid,
            "thread": "sched", "rid": rid, "trace": trace,
            "args": {}, "seq": seq}


def test_fleet_trace_stitches_one_trace_across_replicas():
    tid = "feedbeef" * 4
    # replica A served the first half, B resumed after a hand-off; their
    # perf clocks are wildly different epochs
    ra = _Replica(trace_doc=_trace_doc(
        [_span("prefill", 990.0, "req-1", tid, 1),
         _span("decode_chunk", 991.0, "req-1", tid, 2),
         _span("other", 991.5, "req-9", "cafe" * 8, 3)]))
    rb = _Replica(
        trace_doc=_trace_doc(
            [_span("decode_chunk", 995.0, "req-1", tid, 1)]),
        events_doc={"events": [
            {"kind": "respawn", "ts": time.time(), "seq": 1,
             "replica": "x"},
            {"kind": "handoff", "ts": time.time(), "seq": 2,
             "rid": "req-1", "trace": tid}],
            "next_seq": 2, "oldest_seq": 1, "capacity": 16})
    try:
        reg = Registry([ra.addr, rb.addr])
        fs = FleetScraper(reg, timeout=2.0)
        doc = fs.fleet_trace()
        assert doc["displayTimeUnit"] == "ms"
        assert doc["fleet"][ra.addr] == {"up": True, "spans": 3}
        assert doc["fleet"][rb.addr] == {"up": True, "spans": 1}
        assert doc["fleet"]["router"]["up"] is True

        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        mine = [e for e in spans
                if e.get("args", {}).get("trace_id") == tid]
        # one trace id, spans from BOTH replica processes (distinct pids)
        assert len({e["pid"] for e in mine}) == 2
        assert all(e["args"]["request_id"] == "req-1" for e in mine)
        # wall-clock alignment: every shifted ts lands near now (µs)
        now_us = time.time() * 1e6
        for e in mine:
            assert abs(e["ts"] - now_us) < 120e6, e
        # journal instant markers ride the timeline
        marks = {e["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "i"}
        assert {"event:respawn", "event:handoff"} <= marks

        # trace filter: other traces' spans gone, traceless journal
        # markers (the fleet context) kept
        doc = fs.fleet_trace(trace=tid)
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert {e["args"]["trace_id"] for e in spans} == {tid}
        marks = {e["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "i"}
        assert "event:respawn" in marks
    finally:
        ra.close()
        rb.close()


def test_fleet_events_keyed_by_replica():
    r1 = _Replica(events_doc={"events": [
        {"kind": "spawn", "ts": 1.0, "seq": 1}],
        "next_seq": 1, "oldest_seq": 1, "capacity": 16})
    try:
        reg = Registry([r1.addr, "127.0.0.1:1"])   # second one dead
        fs = FleetScraper(reg, timeout=1.0)
        doc = fs.fleet_events()
        assert doc["replicas"][r1.addr]["events"][0]["kind"] == "spawn"
        assert doc["replicas"]["127.0.0.1:1"] == {"up": False}
        assert "next_seq" in doc["router"]
    finally:
        r1.close()


# --- integration: the router's public endpoints ---------------------------

@pytest.fixture
def router_server():
    """A real router handler over fake replicas — the surface
    fleet_top/trace_dump/Prometheus actually scrape."""
    from dllama_tpu.router.service import RouterState, make_handler

    replicas, servers = [], []

    def make(n=2, *, fleet_scope_default=False, **replica_kw):
        for _ in range(n):
            replicas.append(_Replica(**replica_kw))
        reg = Registry([r.addr for r in replicas])
        state = RouterState(reg,
                            fleet_scope_default=fleet_scope_default)
        srv = http.server.ThreadingHTTPServer(
            ("127.0.0.1", 0), make_handler(state))
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
        return (state, replicas,
                f"http://127.0.0.1:{srv.server_address[1]}")

    yield make
    for s in servers:
        s.shutdown()
        s.server_close()
    for r in replicas:
        r.close()


def _get(base, path, headers=None):
    req = urllib.request.Request(base + path, headers=headers or {})
    return urllib.request.urlopen(req, timeout=30)


def test_router_metrics_scope_negotiation(router_server):
    state, replicas, base = router_server(2)
    # default scope=self: no replica labels
    with _get(base, "/metrics") as r:
        doc = json.loads(r.read())
    assert "replicas" not in doc and "uptime_s" in doc
    # explicit fleet scope: both expositions federated
    with _get(base, "/metrics?scope=fleet") as r:
        doc = json.loads(r.read())
    assert set(doc["replicas"]) == {x.addr for x in replicas}
    with _get(base, "/metrics?scope=fleet&format=prometheus") as r:
        assert "version=0.0.4" in r.headers["Content-Type"]
        text = r.read().decode()
    for x in replicas:
        assert f'replica="{x.addr}"' in text
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(base, "/metrics?scope=banana")
    assert ei.value.code == 400


def test_router_fleet_scope_default_is_pod_mode(router_server):
    state, replicas, base = router_server(1, fleet_scope_default=True)
    # serve-pod mode: a bare public scrape IS the fleet scrape
    with _get(base, "/metrics") as r:
        doc = json.loads(r.read())
    assert replicas[0].addr in doc["replicas"]
    with _get(base, "/metrics?scope=self") as r:
        doc = json.loads(r.read())
    assert "replicas" not in doc


def test_router_debug_trace_and_events_endpoints(router_server):
    tid = "abcd" * 8
    state, replicas, base = router_server(
        1, trace_doc=_trace_doc([_span("decode_chunk", 1.0,
                                       "req-2", tid, 1)]))
    obs_trace.clear()
    with _get(base, "/debug/trace?scope=fleet") as r:
        doc = json.loads(r.read())
    assert replicas[0].addr in doc["fleet"]
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert any(e["args"].get("trace_id") == tid for e in spans)
    # replica-style raw cursor on the router's own ring
    with _get(base, "/debug/trace?since=0") as r:
        doc = json.loads(r.read())
    assert "next_seq" in doc and "perf_now" in doc
    # journal endpoint with cursor
    cur = obs_events.snapshot()["next_seq"]
    obs_events.emit("eject", replica="r-test", why="probe")
    with _get(base, f"/debug/events?since={cur}") as r:
        doc = json.loads(r.read())
    assert [e["kind"] for e in doc["events"]] == ["eject"]
    with _get(base, "/debug/events?scope=fleet") as r:
        doc = json.loads(r.read())
    assert replicas[0].addr in doc["replicas"]


# --- DLREQ01 carriage: trace survives park/hand-off -----------------------

@pytest.mark.router
def test_handoff_and_preempt_keep_trace_id():
    import jax

    from dllama_tpu.models.config import tiny_config
    from dllama_tpu.models.params import init_params
    from dllama_tpu.parallel.mesh import make_mesh
    from dllama_tpu.runtime.engine import Engine
    from dllama_tpu.runtime.faults import injected
    from dllama_tpu.runtime.scheduler import PRIORITY_LEVELS, SlotScheduler
    from dllama_tpu.runtime.snapshot import loads_request

    cfg = tiny_config(seq_len=64)
    page = 4
    pages_per_slot = -(-cfg.seq_len // page)

    def mk(batch=1):
        eng = Engine(cfg, init_params(cfg, seed=4),
                     mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                     batch=batch,
                     kv_pages=batch * pages_per_slot + 1,
                     kv_page_size=page)
        return SlotScheduler(eng, prefill_chunk=4, max_wait_ms=20.0,
                             decode_burst=4, preempt=True,
                             preempt_age_ms=0.0, prefix_reuse=False)

    sa, sb = mk(), mk()
    tid = obs_trace.new_trace_id()
    try:
        # ---- hand-off leg: export from A, import into B ----
        ev0 = obs_events.snapshot()["next_seq"]
        with injected("engine.device_step=delay:0.05"):
            t = sa.submit([5, 9, 2], 30, temperature=0.0)
            obs_trace.set_trace(t.rid, tid)
            it = t.tokens()
            for _ in range(4):
                next(it)
            records = sa.handoff_export_all()
        list(it)
        # the record itself carries the trace id (cross-process carrier)
        meta, _ = loads_request(records[t.rid])
        assert meta["extra"]["trace_id"] == tid

        t2, extra = sb.import_request(records[t.rid])
        assert extra["trace_id"] == tid
        # the importing process re-established rid→trace: resume spans
        # and a same-id stitched dump need no further plumbing
        assert obs_trace.trace_of(t2.rid) == tid
        list(t2.tokens())
        assert t2.finish == "length"

        evs = obs_events.snapshot(ev0)["events"]
        hand = [e for e in evs if e["kind"] == "handoff"
                and e.get("rid") == t.rid]
        dirs = {e.get("direction") for e in hand}
        assert {"export", "import"} <= dirs, evs
        assert all(e.get("trace") == tid for e in hand), hand

        # spans recorded during the resume carry the trace id
        resumed = [s for s in obs_trace.raw()["spans"]
                   if s.get("rid") == t2.rid and s.get("trace") == tid]
        assert resumed, "no resume span carried the trace id"

        # ---- preempt-park-resume leg on B: same trace end to end ----
        ev1 = obs_events.snapshot()["next_seq"]
        done = {}

        def run(key, prompt, n, prio):
            tk = sb.submit(prompt, n, priority=prio)
            if key == "batch":
                obs_trace.set_trace(tk.rid, tid)
            done[key] = (tk, list(tk.tokens()))

        from dllama_tpu.runtime.faults import FAULTS
        FAULTS.install("engine.device_step=delay:0.05x1000")
        try:
            bt = threading.Thread(target=run, args=(
                "batch", [7, 3, 11], 24, PRIORITY_LEVELS["batch"]))
            bt.start()
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if sb.occupancy()["active"] == 1:
                    break
                time.sleep(0.01)
            time.sleep(0.2)
            run("it", [2, 4, 6], 4, PRIORITY_LEVELS["interactive"])
        finally:
            FAULTS.clear()
        bt.join(240)
        assert done["batch"][0].finish == "length"

        evs = obs_events.snapshot(ev1)["events"]
        pre = [e for e in evs if e["kind"] == "preempt"]
        res = [e for e in evs if e["kind"] == "resume"]
        assert pre and res, evs
        assert any(e.get("trace") == tid for e in pre), pre
        assert any(e.get("trace") == tid for e in res), res
        # causal order: the park precedes the re-admission
        assert min(e["seq"] for e in pre) < max(e["seq"] for e in res)
    finally:
        sa.close()
        sb.close()


# --- tools ----------------------------------------------------------------

class _FakeRouter:
    """Canned router: the three surfaces fleet_top polls plus the
    stitched fleet trace for trace_dump --fleet."""

    def __init__(self):
        tid = "0123abcd" * 4
        self.health = {"status": "ok", "available": 2, "total": 2,
                       "model": "tiny", "backends": [
                           {"addr": "127.0.0.1:1001", "ejected": False,
                            "draining": False, "retiring": False},
                           {"addr": "127.0.0.1:1002", "ejected": False,
                            "draining": False, "retiring": False}]}
        self.fed = {"scope": "fleet", "router": {"uptime_s": 5.0},
                    "replicas": {
                        "127.0.0.1:1001": {"up": True, "metrics": {
                            "sched_slots_occupied": 2,
                            "sched_queue_depth": 1,
                            "kv_pages_in_use": 30, "kv_pages_total": 60,
                            "sched_goodput_ratio": 0.83,
                            "slo_burn_rate": {"ttft/fast": 0.4,
                                              "ttft/slow": 1.2},
                            "requests_served": 11}},
                        "127.0.0.1:1002": {"up": False, "stale": True,
                                           "stale_age_s": 3.0,
                                           "metrics": {
                                               "requests_served": 4}}}}
        self.events = {"scope": "fleet",
                       "router": {"events": [
                           {"kind": "eject", "ts": time.time(), "seq": 1,
                            "replica": "127.0.0.1:1002",
                            "why": "probe_failed"}],
                           "next_seq": 1, "oldest_seq": 1,
                           "capacity": 16},
                       "replicas": {"127.0.0.1:1001": {"events": [
                           {"kind": "resume", "ts": time.time(),
                            "seq": 3, "rid": "r-1"}],
                           "next_seq": 3, "oldest_seq": 1,
                           "capacity": 16}}}
        self.fleet_trace = {
            "displayTimeUnit": "ms",
            "fleet": {"router": {"up": True, "spans": 0},
                      "127.0.0.1:1001": {"up": True, "spans": 1},
                      "127.0.0.1:1002": {"up": True, "spans": 1}},
            "traceEvents": [
                {"name": "decode_chunk", "ph": "X", "ts": 1.0,
                 "dur": 2.0, "pid": 2, "tid": 1,
                 "args": {"trace_id": tid, "request_id": "r-1"}},
                {"name": "decode_chunk", "ph": "X", "ts": 9.0,
                 "dur": 2.0, "pid": 3, "tid": 1,
                 "args": {"trace_id": tid, "request_id": "r-1"}},
                {"name": "event:respawn", "ph": "i", "s": "p",
                 "ts": 5.0, "pid": 1, "tid": 0, "args": {}}]}
        outer = self

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.startswith("/health"):
                    doc = outer.health
                elif self.path.startswith("/metrics"):
                    doc = outer.fed
                elif self.path.startswith("/debug/events"):
                    doc = outer.events
                elif self.path.startswith("/debug/trace"):
                    doc = outer.fleet_trace
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = json.dumps(doc).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.base = f"http://127.0.0.1:{self.server.server_address[1]}"
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def test_fleet_top_once(capsys):
    tool = _load_tool("fleet_top")
    fr = _FakeRouter()
    try:
        assert tool.main([fr.base, "--once"]) == 0
        out = capsys.readouterr().out
        assert "available=2/2" in out
        # the healthy replica's row: occupancy, kv%, goodput, worst burn
        assert "127.0.0.1:1001" in out and "50.0" in out \
            and "0.830" in out and "1.20" in out
        # the stale one renders marked, not dropped
        assert "~DOWN" in out
        # event tail merges router + replica journals
        assert "eject" in out and "resume" in out
    finally:
        fr.close()
    # unreachable router → clean failure
    assert tool.main(["http://127.0.0.1:1", "--once"]) == 1


def test_trace_dump_fleet(tmp_path, capsys):
    tool = _load_tool("trace_dump")
    fr = _FakeRouter()
    try:
        out = tmp_path / "fleet.json"
        assert tool.main([fr.base, "--fleet", "-o", str(out)]) == 0
        doc = json.loads(out.read_text())
        assert doc["fleet"]["127.0.0.1:1001"]["spans"] == 1
        printed = capsys.readouterr().out
        assert "3 process(es)" in printed
        # the migrated request is called out: one trace, two replicas
        assert "span multiple replicas" in printed
        assert "0123abcd" in printed
    finally:
        fr.close()
    assert tool.main(["http://127.0.0.1:1", "--fleet",
                      "-o", str(tmp_path / "x.json")]) == 1
