"""Sequence-parallel attention: sp-sharded ≡ single-device equivalence.

The N-shard ≡ 1-shard invariance pattern (commands-test.cpp:30-69), lifted
to the sequence axis — a capability beyond the reference (SURVEY §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.models.transformer import forward, init_kv_cache
from dllama_tpu.ops.attention import gqa_attention
from dllama_tpu.ops.sp_attention import sp_gqa_attention
from dllama_tpu.parallel import sharding as sh
from dllama_tpu.parallel.mesh import active_mesh, make_mesh
from dllama_tpu.runtime.engine import Engine
from dllama_tpu.sampling import Sampler

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")


def _qkv(b=1, hq=4, hkv=2, s=32, dh=8, t=1, seed=0):
    r = np.random.RandomState(seed)
    q = jnp.asarray(r.randn(b, hq, t, dh), jnp.float32)
    k = jnp.asarray(r.randn(b, hkv, s, dh), jnp.float32)
    v = jnp.asarray(r.randn(b, hkv, s, dh), jnp.float32)
    return q, k, v


class TestOp:
    @needs_8
    @pytest.mark.parametrize("sp,pos,t", [(8, 17, 1), (4, 0, 1), (8, 3, 8)])
    def test_matches_local_attention(self, sp, pos, t):
        mesh = make_mesh(tp=1, sp=sp, dp=1, devices=jax.devices()[:sp])
        q, k, v = _qkv(s=32, t=t)
        ref = gqa_attention(q, k, v, jnp.int32(pos), t)
        out = jax.jit(lambda q, k, v: sp_gqa_attention(
            q, k, v, jnp.int32(pos), t, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @needs_8
    def test_with_tp_and_sp(self):
        """2-D mesh: heads on tp, sequence on sp."""
        mesh = make_mesh(tp=2, sp=4, dp=1, devices=jax.devices()[:8])
        q, k, v = _qkv(hq=4, hkv=2, s=32, t=1)
        ref = gqa_attention(q, k, v, jnp.int32(9), 1)
        out = jax.jit(lambda q, k, v: sp_gqa_attention(
            q, k, v, jnp.int32(9), 1, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @needs_8
    def test_empty_shards_no_nan(self):
        """pos=0: only shard 0 has any unmasked keys; others must
        contribute exact zeros, not NaNs."""
        mesh = make_mesh(tp=1, sp=8, dp=1, devices=jax.devices()[:8])
        q, k, v = _qkv(s=64)
        out = jax.jit(lambda q, k, v: sp_gqa_attention(
            q, k, v, jnp.int32(0), 1, mesh))(q, k, v)
        assert np.all(np.isfinite(np.asarray(out)))
        ref = gqa_attention(q, k, v, jnp.int32(0), 1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


class TestModel:
    @needs_8
    def test_sp_forward_equivalence(self):
        """Whole-model forward on an sp mesh ≡ unsharded forward."""
        cfg = tiny_config(dim=64, hidden_dim=96, n_layers=2, n_heads=4,
                          n_kv_heads=2, vocab_size=128, seq_len=64)
        params = init_params(cfg, seed=0)
        tokens = jnp.asarray([[3, 1, 7, 2, 9]], jnp.int32)

        ref, _ = forward(params, cfg, tokens, init_kv_cache(cfg, 1), jnp.int32(0))

        mesh = make_mesh(tp=1, sp=8, dp=1, devices=jax.devices()[:8])
        placed = sh.place_params(params, cfg, mesh)
        cache = jax.device_put(init_kv_cache(cfg, 1),
                               sh.kv_cache_sharding(mesh, "sp"))
        with active_mesh(mesh):
            out, _ = jax.jit(lambda p, c, t: forward(p, cfg, t, c, jnp.int32(0)))(
                placed, cache, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    @needs_8
    def test_engine_sp_decode_equivalence(self):
        """Engine on an sp=4×tp=2 mesh generates the same greedy tokens as
        the single-device engine."""
        cfg = tiny_config(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                          n_kv_heads=2, vocab_size=128, seq_len=64)
        params = init_params(cfg, seed=1)

        def toks(engine):
            s = Sampler(cfg.vocab_size, 0.0, 0.9, 0)
            return [t for t, _ in engine.generate([5, 9, 2], steps=12, sampler=s)]

        ref = toks(Engine(cfg, params))
        mesh = make_mesh(tp=2, sp=4, dp=1, devices=jax.devices()[:8])
        got = toks(Engine(cfg, params, mesh=mesh))
        assert ref == got
