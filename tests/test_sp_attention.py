"""Sequence-parallel attention: sp-sharded ≡ single-device equivalence.

The N-shard ≡ 1-shard invariance pattern (commands-test.cpp:30-69), lifted
to the sequence axis — a capability beyond the reference (SURVEY §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.models.transformer import forward, init_kv_cache
from dllama_tpu.ops.attention import gqa_attention
from dllama_tpu.ops.sp_attention import sp_gqa_attention
from dllama_tpu.parallel import sharding as sh
from dllama_tpu.parallel.mesh import active_mesh, make_mesh
from dllama_tpu.runtime.engine import Engine
from dllama_tpu.sampling import Sampler

needs_8 = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")


def _qkv(b=1, hq=4, hkv=2, s=32, dh=8, t=1, seed=0):
    r = np.random.RandomState(seed)
    q = jnp.asarray(r.randn(b, hq, t, dh), jnp.float32)
    k = jnp.asarray(r.randn(b, hkv, s, dh), jnp.float32)
    v = jnp.asarray(r.randn(b, hkv, s, dh), jnp.float32)
    return q, k, v


class TestOp:
    @needs_8
    @pytest.mark.parametrize("sp,pos,t", [(8, 17, 1), (4, 0, 1), (8, 3, 8)])
    def test_matches_local_attention(self, sp, pos, t):
        mesh = make_mesh(tp=1, sp=sp, dp=1, devices=jax.devices()[:sp])
        q, k, v = _qkv(s=32, t=t)
        ref = gqa_attention(q, k, v, jnp.int32(pos), t)
        out = jax.jit(lambda q, k, v: sp_gqa_attention(
            q, k, v, jnp.int32(pos), t, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @needs_8
    def test_with_tp_and_sp(self):
        """2-D mesh: heads on tp, sequence on sp."""
        mesh = make_mesh(tp=2, sp=4, dp=1, devices=jax.devices()[:8])
        q, k, v = _qkv(hq=4, hkv=2, s=32, t=1)
        ref = gqa_attention(q, k, v, jnp.int32(9), 1)
        out = jax.jit(lambda q, k, v: sp_gqa_attention(
            q, k, v, jnp.int32(9), 1, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


    @needs_8
    @pytest.mark.parametrize("pos", [0, 100, 4095, 8191, 16383])
    def test_blocked_long_chunk_matches_dense(self, pos):
        """Long local chunks (>= the blocked-decode threshold) walk only
        live KV blocks per shard; results must equal dense one-shot
        attention at every position class, including block boundaries."""
        from dllama_tpu.ops import attention

        mesh = make_mesh(tp=1, sp=4, dp=1, devices=jax.devices()[:4])
        q, k, v = _qkv(s=16384, t=1)   # local chunk 4096 -> blocked path
        ref = gqa_attention(q, k, v, jnp.int32(pos), 1)
        out = jax.jit(lambda q, k, v: sp_gqa_attention(
            q, k, v, jnp.int32(pos), 1, mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @needs_8
    def test_empty_shards_no_nan(self):
        """pos=0: only shard 0 has any unmasked keys; others must
        contribute exact zeros, not NaNs."""
        mesh = make_mesh(tp=1, sp=8, dp=1, devices=jax.devices()[:8])
        q, k, v = _qkv(s=64)
        out = jax.jit(lambda q, k, v: sp_gqa_attention(
            q, k, v, jnp.int32(0), 1, mesh))(q, k, v)
        assert np.all(np.isfinite(np.asarray(out)))
        ref = gqa_attention(q, k, v, jnp.int32(0), 1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


class TestSpCacheUpdate:
    @needs_8
    @pytest.mark.parametrize("pos", [0, 7, 8, 31])
    def test_shard_local_write_equals_plain_update(self, pos):
        from dllama_tpu.ops.attention import update_kv_cache_at
        from dllama_tpu.ops.sp_attention import sp_update_kv_cache_at

        mesh = make_mesh(tp=2, sp=4, dp=1, devices=jax.devices()[:8])
        r = np.random.RandomState(pos)
        L, layer = 3, jnp.int32(1)
        kc = jnp.asarray(r.randn(L, 1, 2, 32, 8), jnp.float32)
        vc = jnp.asarray(r.randn(L, 1, 2, 32, 8), jnp.float32)
        kn = jnp.asarray(r.randn(1, 2, 1, 8), jnp.float32)
        vn = jnp.asarray(r.randn(1, 2, 1, 8), jnp.float32)
        ek, ev = update_kv_cache_at(kc, vc, kn, vn, layer, jnp.int32(pos))
        gk, gv = jax.jit(lambda *a: sp_update_kv_cache_at(
            *a, layer, jnp.int32(pos), mesh))(kc, vc, kn, vn)
        np.testing.assert_array_equal(np.asarray(gk), np.asarray(ek))
        np.testing.assert_array_equal(np.asarray(gv), np.asarray(ev))

    @needs_8
    def test_multi_token_write_rejected(self):
        from dllama_tpu.ops.sp_attention import sp_update_kv_cache_at

        mesh = make_mesh(tp=2, sp=4, dp=1, devices=jax.devices()[:8])
        kc = jnp.zeros((2, 1, 2, 32, 8), jnp.float32)
        kn = jnp.zeros((1, 2, 3, 8), jnp.float32)  # T=3: would straddle shards
        with pytest.raises(ValueError, match="one decode step"):
            sp_update_kv_cache_at(kc, kc, kn, kn, jnp.int32(0), jnp.int32(0), mesh)


class TestRing:
    """ring_attention: sharded-Q prefill over rotating KV blocks must equal
    dense causal attention (the same invariance pattern, now with queries
    sequence-sharded too)."""

    @needs_8
    @pytest.mark.parametrize("sp,t,pos0", [(4, 32, 0), (8, 64, 0), (4, 16, 8)])
    def test_matches_dense_causal(self, sp, t, pos0):
        from dllama_tpu.ops.sp_attention import ring_attention

        mesh = make_mesh(tp=1, sp=sp, dp=1, devices=jax.devices()[:sp])
        r = np.random.RandomState(1)
        b, hq, hkv, dh = 1, 4, 2, 8
        q = jnp.asarray(r.randn(b, hq, t, dh), jnp.float32)
        k = jnp.asarray(r.randn(b, hkv, t, dh), jnp.float32)
        v = jnp.asarray(r.randn(b, hkv, t, dh), jnp.float32)
        # dense reference: full causal self-attention over positions
        # [pos0, pos0+t) — gqa_attention with the cache holding k/v at
        # offset... simplest exact reference: manual masked softmax
        g = hq // hkv
        qf = np.asarray(q, np.float64).reshape(b, hkv, g, t, dh)
        kf = np.asarray(k, np.float64)
        scores = np.einsum("bhgtd,bhsd->bhgts", qf, kf) / np.sqrt(dh)
        mask = np.tril(np.ones((t, t), bool))
        scores = np.where(mask[None, None, None], scores, -np.inf)
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("bhgts,bhsd->bhgtd", p, np.asarray(v, np.float64))
        ref = ref.reshape(b, hq, t, dh)

        out = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh, pos0=pos0,
            q_spec=jax.sharding.PartitionSpec("dp", "tp", "sp", None),
        ))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)

    @needs_8
    def test_ring_with_tp(self):
        from dllama_tpu.ops.sp_attention import ring_attention

        mesh = make_mesh(tp=2, sp=4, dp=1, devices=jax.devices()[:8])
        r = np.random.RandomState(2)
        b, hq, hkv, t, dh = 1, 4, 2, 32, 8
        q = jnp.asarray(r.randn(b, hq, t, dh), jnp.float32)
        k = jnp.asarray(r.randn(b, hkv, t, dh), jnp.float32)
        v = jnp.asarray(r.randn(b, hkv, t, dh), jnp.float32)
        out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))(q, k, v)
        # reference via the (already-validated) one-round sp path with a
        # full cache and t queries at pos 0
        ref = gqa_attention(q, k, v, jnp.int32(0), t)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


class TestModel:
    @needs_8
    def test_sp_forward_equivalence(self):
        """Whole-model forward on an sp mesh ≡ unsharded forward."""
        cfg = tiny_config(dim=64, hidden_dim=96, n_layers=2, n_heads=4,
                          n_kv_heads=2, vocab_size=128, seq_len=64)
        params = init_params(cfg, seed=0)
        tokens = jnp.asarray([[3, 1, 7, 2, 9]], jnp.int32)

        ref, _ = forward(params, cfg, tokens, init_kv_cache(cfg, 1), jnp.int32(0))

        mesh = make_mesh(tp=1, sp=8, dp=1, devices=jax.devices()[:8])
        placed = sh.place_params(params, cfg, mesh)
        cache = jax.device_put(init_kv_cache(cfg, 1),
                               sh.kv_cache_sharding(mesh, "sp"))
        with active_mesh(mesh):
            out, _ = jax.jit(lambda p, c, t: forward(p, cfg, t, c, jnp.int32(0)))(
                placed, cache, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    @needs_8
    def test_engine_sp_decode_equivalence(self):
        """Engine on an sp=4×tp=2 mesh generates the same greedy tokens as
        the single-device engine."""
        cfg = tiny_config(dim=64, hidden_dim=128, n_layers=2, n_heads=4,
                          n_kv_heads=2, vocab_size=128, seq_len=64)
        params = init_params(cfg, seed=1)

        def toks(engine):
            s = Sampler(cfg.vocab_size, 0.0, 0.9, 0)
            return [t for t, _ in engine.generate([5, 9, 2], steps=12, sampler=s)]

        ref = toks(Engine(cfg, params))
        mesh = make_mesh(tp=2, sp=4, dp=1, devices=jax.devices()[:8])
        got = toks(Engine(cfg, params, mesh=mesh))
        assert ref == got

    @needs_8
    def test_engine_sp_long_cache_blocked_decode(self):
        """Engine decode over an sp mesh whose local chunk crosses the
        blocked-decode threshold (seq 16384 / sp 4 = 4096): the per-shard
        live-prefix block walk must reproduce single-device greedy tokens
        end to end."""
        cfg = tiny_config(dim=32, hidden_dim=64, n_layers=2, n_heads=4,
                          n_kv_heads=2, vocab_size=64, seq_len=16384)
        params = init_params(cfg, seed=3)

        def toks(engine):
            s = Sampler(cfg.vocab_size, 0.0, 0.9, 0)
            return [t for t, _ in engine.generate([5, 9, 2], steps=8, sampler=s)]

        ref = toks(Engine(cfg, params))
        mesh = make_mesh(tp=1, sp=4, dp=1, devices=jax.devices()[:4])
        got = toks(Engine(cfg, params, mesh=mesh))
        assert ref == got

    @needs_8
    def test_engine_sp_multi_turn_continuation(self):
        """Chat-style incremental prefill on an sp mesh: the second turn's
        continuation prefill (pos > 0, T > 1 — the non-ring sp prefill
        path) must match a single full prefill, like the single-device
        engine guarantees."""
        cfg = tiny_config(dim=64, hidden_dim=96, n_layers=2, n_heads=4,
                          n_kv_heads=2, vocab_size=128, seq_len=64)
        params = init_params(cfg, seed=4)
        mesh = make_mesh(tp=1, sp=4, dp=1, devices=jax.devices()[:4])
        e = Engine(cfg, params, mesh=mesh)
        e.prefill([4, 7, 1])
        l_cont, _ = e.prefill([9, 3])
        e2 = Engine(cfg, params, mesh=mesh)
        l_full, _ = e2.prefill([4, 7, 1, 9, 3])
        np.testing.assert_allclose(l_cont, l_full, atol=1e-4, rtol=1e-3)
        # and both match the single-device engine
        l_ref, _ = Engine(cfg, params).prefill([4, 7, 1, 9, 3])
        np.testing.assert_allclose(l_full, l_ref, atol=1e-4, rtol=1e-3)

    @needs_8
    def test_engine_ring_prefill_equivalence(self):
        """A long from-scratch prompt on an sp mesh takes the ring-prefill
        path (sequence-sharded tokens, blockwise attention) and still
        produces the single-device logits + greedy continuation."""
        cfg = tiny_config(dim=64, hidden_dim=96, n_layers=2, n_heads=4,
                          n_kv_heads=2, vocab_size=128, seq_len=128)
        params = init_params(cfg, seed=2)
        rng = np.random.RandomState(0)
        prompt = rng.randint(1, 128, 40).tolist()  # bucket 64: divisible by sp=8

        e1 = Engine(cfg, params)
        mesh = make_mesh(tp=1, sp=8, dp=1, devices=jax.devices()[:8])
        esp = Engine(cfg, params, mesh=mesh)
        assert hasattr(esp, "_step_ring")
        l1, _ = e1.prefill(prompt[:])
        lsp, _ = esp.prefill(prompt[:])
        assert esp.pos == len(prompt)
        np.testing.assert_allclose(lsp, l1, rtol=0,
                                   atol=1e-4 + 1e-4 * np.abs(l1).max())
        # the cache the ring prefill wrote must support an exact decode
        s1 = Sampler(cfg.vocab_size, 0.0, 0.9, 0)
        s2 = Sampler(cfg.vocab_size, 0.0, 0.9, 0)
        t1 = [int(s1.sample(e1.decode_one(int(s1.sample(l1[0])))[0][0])) for _ in range(1)]
        tsp = [int(s2.sample(esp.decode_one(int(s2.sample(lsp[0])))[0][0])) for _ in range(1)]
        assert t1 == tsp
