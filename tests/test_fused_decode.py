"""One-dispatch decode tests (docs/PERF.md "one-dispatch decode"): the
fused page-walk paged-attention Pallas kernel (ops/attention.py
``fused_paged_attention``) and the on-device sampling stage
(sampling.sample_on_device + the engine's device-resident RNG key
chain).

Contracts pinned here on CPU — the kernel runs in Pallas interpret mode
(``DLLAMA_FUSED_ATTN=interp``: same kernel logic, no TPU needed):

* **kernel parity** — the fused kernel matches the gather +
  rows-ceiling reference on a random ragged fixture, dense and int8
  pools, at a non-zero layer (tolerance scaled to the reference
  magnitude: the two implementations associate the bf16 online-softmax
  folds differently, so 2e-5 elementwise is the wrong bar);
* **byte parity** — greedy decode through the paged scheduler is
  token-identical with the kernel forced on vs off, overlap on and
  off, dense and int8 pools (the fused kernel is a dispatch-structure
  change, never a numerics change at argmax granularity);
* **fixed-coin parity** — ``sample_on_device`` picks the same token as
  the host ``sample_with_coin`` for the same coin across a
  temperature × top-p × top-k × mask grid including ties;
* **device key chain** — sampled slot decode is deterministic given
  the engine seed, a snapshot/restore continues the sampled stream
  byte-identically (the device key rides DLSNAP02), hand-off records
  carry the device key + sampling-path flag, and a record from a
  different sampling path is refused before any state is touched.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dllama_tpu.models.config import tiny_config
from dllama_tpu.models.params import init_params
from dllama_tpu.ops.attention import (_rows_ceiling_attention,
                                      fused_paged_attention,
                                      paged_gather_layer, quantize_kv)
from dllama_tpu.parallel.mesh import make_mesh
from dllama_tpu.runtime import snapshot as snapfmt
from dllama_tpu.runtime.engine import Engine
from dllama_tpu.runtime.faults import injected
from dllama_tpu.runtime.scheduler import SlotScheduler
from dllama_tpu.sampling import sample_on_device, sample_with_coin

CFG = tiny_config(seq_len=64)
PAGE = 8
PROMPTS = ([5, 9, 2], [7, 3, 11, 4, 6, 1, 8], [2, 4, 6], [9, 8, 7, 6])


def make_paged_engine(batch=4, page=PAGE, **kw):
    pages_per_slot = -(-CFG.seq_len // page)
    return Engine(CFG, init_params(CFG, seed=4),
                  mesh=make_mesh(tp=1, devices=jax.devices()[:1]),
                  batch=batch,
                  kv_pages=batch * pages_per_slot + 1,
                  kv_page_size=page, **kw)


# -- kernel vs gather reference --------------------------------------------

def _pool_fixture(quantized, b=3, maxp=3, hkv=2, g=2, ps=8, dh=16,
                  nlayers=2):
    npages = 1 + b * maxp
    rng = np.random.RandomState(3)
    table = jnp.asarray(np.arange(1, 1 + b * maxp).reshape(b, maxp),
                        jnp.int32)
    # ragged: one full row, one mid-page, one inside the first page
    pos_rows = jnp.asarray([maxp * ps - 1, ps + ps // 2, 3], jnp.int32)
    q = jnp.asarray(rng.randn(b, hkv * g, 1, dh) * 0.3, jnp.float32)
    if quantized:
        pk, sk = quantize_kv(jnp.asarray(
            rng.randn(nlayers, npages, hkv, ps, dh), jnp.float32))
        pv, sv = quantize_kv(jnp.asarray(
            rng.randn(nlayers, npages, hkv, ps, dh), jnp.float32))
        scales = (sk, sv)
    else:
        pk = jnp.asarray(rng.randn(nlayers, npages, hkv, ps, dh) * 0.3,
                         jnp.bfloat16)
        pv = jnp.asarray(rng.randn(nlayers, npages, hkv, ps, dh) * 0.3,
                         jnp.bfloat16)
        scales = None
    return q, pk, pv, table, pos_rows, scales


@pytest.mark.parametrize("quantized", [False, True],
                         ids=["dense", "kv_int8"])
def test_fused_kernel_matches_gather_reference(quantized):
    """The page-walk kernel and the materialized-gather path compute the
    same attention read — ragged rows, layer 1 of 2 (the layer index
    rides scalar prefetch), dead pages fully masked."""
    q, pk, pv, table, pos_rows, scales = _pool_fixture(quantized)
    layer = jnp.int32(1)
    out = fused_paged_attention(q, pk, pv, layer, table, pos_rows,
                                scales=scales, interpret=True)
    ks, vs = scales if scales is not None else (None, None)
    k_l = paged_gather_layer(pk, layer, table, scale_pool=ks)
    v_l = paged_gather_layer(pv, layer, table, scale_pool=vs)
    ref = _rows_ceiling_attention(q, k_l, v_l, pos_rows)
    assert out.shape == ref.shape == q.shape
    tol = 1e-2 * max(float(np.abs(np.asarray(ref, np.float32)).max()), 1e-3)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=tol)


def test_fused_kernel_under_jit():
    """The kernel composes with jit (the engine always calls it inside a
    compiled step) and stays deterministic across calls."""
    q, pk, pv, table, pos_rows, scales = _pool_fixture(False)

    @jax.jit
    def step(q):
        return fused_paged_attention(q, pk, pv, jnp.int32(0), table,
                                     pos_rows, interpret=True)

    a = np.asarray(step(q))
    b = np.asarray(step(q))
    np.testing.assert_array_equal(a, b)
    ref = _rows_ceiling_attention(
        q, paged_gather_layer(pk, jnp.int32(0), table),
        paged_gather_layer(pv, jnp.int32(0), table), pos_rows)
    tol = 1e-2 * max(float(np.abs(np.asarray(ref, np.float32)).max()), 1e-3)
    np.testing.assert_allclose(a.astype(np.float32),
                               np.asarray(ref, np.float32), atol=tol)


# -- e2e greedy byte parity: fused vs fallback -----------------------------

def _sched_streams(overlap, kv_dtype, max_new=20):
    eng = make_paged_engine(**({"kv_dtype": kv_dtype} if kv_dtype else {}))
    sched = SlotScheduler(eng, prefill_chunk=8, max_wait_ms=20.0,
                          overlap=overlap)
    out = [None] * len(PROMPTS)

    def go(i):
        t = sched.submit(list(PROMPTS[i]), max_new)
        out[i] = list(t.tokens())

    ths = [threading.Thread(target=go, args=(i,))
           for i in range(len(PROMPTS))]
    for th in ths:
        th.start()
    for th in ths:
        th.join()
    sched.close()
    assert all(len(s) == max_new for s in out)
    return out


@pytest.mark.parametrize("kv_dtype", [None, "q8"], ids=["dense", "kv_int8"])
@pytest.mark.parametrize("overlap", [False, True],
                         ids=["sync", "overlap"])
def test_greedy_byte_parity_fused_vs_fallback(monkeypatch, overlap,
                                              kv_dtype):
    """Ragged staggered greedy decode through the paged scheduler: the
    emitted streams with the fused kernel forced on (interpret mode)
    must be byte-identical to the gather fallback, overlap on and off,
    dense and int8 pools."""
    monkeypatch.setenv("DLLAMA_FUSED_ATTN", "interp")
    fused = _sched_streams(overlap, kv_dtype)
    monkeypatch.setenv("DLLAMA_FUSED_ATTN", "off")
    fallback = _sched_streams(overlap, kv_dtype)
    assert fused == fallback


# -- fixed-coin sampling parity host vs device -----------------------------

def test_fixed_coin_sampling_parity():
    """For the same uniform coin, sample_on_device picks the same token
    as the host sample_with_coin across the sampling-mode grid —
    greedy, plain multinomial, nucleus, top-k (with ties at the bar),
    and the optional vocab keep-mask."""
    rng = np.random.RandomState(11)
    v = 48
    cases = [(t, p, k) for t in (0.0, 0.4, 1.0)
             for p in (0.0, 0.5, 0.9, 1.0)
             for k in (0, 3, v)]
    n = len(cases)
    logits = (rng.randn(n, v) * 2.0).astype(np.float32)
    logits[:, 7] = logits[:, 3]  # ties through top-k and the stable sort
    coins = rng.rand(n).astype(np.float32)
    temps = np.asarray([c[0] for c in cases], np.float32)
    topps = np.asarray([c[1] for c in cases], np.float32)
    topks = np.asarray([c[2] for c in cases], np.int32)
    mask = np.ones(v, bool)
    mask[::7] = False
    for m in (None, mask):
        host = [sample_with_coin(logits[i], float(coins[i]),
                                 temperature=float(temps[i]),
                                 topp=float(topps[i]), topk=int(topks[i]),
                                 mask=m)
                for i in range(n)]
        dev = sample_on_device(
            jnp.asarray(logits), jnp.asarray(coins), jnp.asarray(temps),
            jnp.asarray(topps), jnp.asarray(topks),
            mask=None if m is None else jnp.asarray(m))
        assert [int(x) for x in np.asarray(dev)] == host, \
            f"device/host divergence (mask={m is not None})"


def test_identity_mask_is_identity():
    """The all-True vocab mask (the grammar seam's identity) changes no
    decision on either path."""
    rng = np.random.RandomState(5)
    v = 32
    logits = (rng.randn(6, v) * 1.5).astype(np.float32)
    coins = rng.rand(6).astype(np.float32)
    temps = np.full(6, 0.8, np.float32)
    topps = np.full(6, 0.9, np.float32)
    topks = np.zeros(6, np.int32)
    ident = np.ones(v, bool)
    no_mask = sample_on_device(jnp.asarray(logits), jnp.asarray(coins),
                               jnp.asarray(temps), jnp.asarray(topps),
                               jnp.asarray(topks))
    with_mask = sample_on_device(jnp.asarray(logits), jnp.asarray(coins),
                                 jnp.asarray(temps), jnp.asarray(topps),
                                 jnp.asarray(topks), mask=jnp.asarray(ident))
    np.testing.assert_array_equal(np.asarray(no_mask), np.asarray(with_mask))
    for i in range(6):
        assert sample_with_coin(
            logits[i], float(coins[i]), temperature=0.8, topp=0.9,
            mask=ident) == int(np.asarray(no_mask)[i])


# -- device RNG key chain: determinism, snapshot, hand-off -----------------

def _sampled_decode(eng, n_steps, b=2):
    """Prefill PROMPTS[:b] rows, then ``n_steps`` sampled pure-decode
    slot_steps feeding each row its own previous sample.  Returns the
    (n_steps, b) emitted ids plus the loop state for continuation."""
    ps = PAGE
    maxp = -(-CFG.seq_len // ps)
    ptab = np.asarray(
        1 + np.arange(b * maxp).reshape(b, maxp), np.int32)
    temps = np.full(b, 0.8, np.float32)
    topps = np.full(b, 0.9, np.float32)
    width = max(len(p) for p in PROMPTS[:b])
    toks = np.zeros((b, width), np.int32)
    n_valid = np.zeros(b, np.int32)
    for i, p in enumerate(PROMPTS[:b]):
        toks[i, :len(p)] = p
        n_valid[i] = len(p)
    pos = np.zeros(b, np.int32)
    first = eng.slot_step(toks, pos, n_valid, temps_np=temps,
                          topps_np=topps, page_tables_np=ptab)
    pos = pos + n_valid
    cur = first[-1]
    out = [cur.copy()]
    for _ in range(n_steps - 1):
        t = eng.slot_step(cur[:, None].astype(np.int32), pos,
                          np.ones(b, np.int32), temps_np=temps,
                          topps_np=topps, page_tables_np=ptab)
        pos = pos + 1
        cur = t[-1]
        out.append(cur.copy())
    return np.stack(out), (cur, pos, ptab, temps, topps)


def _continue_decode(eng, state, n_steps):
    cur, pos, ptab, temps, topps = state
    b = len(cur)
    out = []
    for _ in range(n_steps):
        t = eng.slot_step(cur[:, None].astype(np.int32), pos,
                          np.ones(b, np.int32), temps_np=temps,
                          topps_np=topps, page_tables_np=ptab)
        pos = pos + 1
        cur = t[-1]
        out.append(cur.copy())
    return np.stack(out), (cur, pos, ptab, temps, topps)


def test_sampled_decode_deterministic_across_engines():
    """Two engines built from the same seed thread the same device key
    chain: sampled slot decode emits identical streams — the property
    that makes on-device sampling snapshot/hand-off safe at all."""
    a, _ = _sampled_decode(make_paged_engine(batch=2), 8)
    b, _ = _sampled_decode(make_paged_engine(batch=2), 8)
    np.testing.assert_array_equal(a, b)
    assert len(np.unique(a)) > 1  # actually sampling, not a constant


def test_sampled_stream_survives_snapshot_restore(tmp_path):
    """DLSNAP02 carries the device RNG key beside the host stream: a
    restored engine continues the sampled stream byte-identically to
    the uninterrupted run."""
    eng = make_paged_engine(batch=2)
    head, state = _sampled_decode(eng, 4)
    path = tmp_path / "mid.dlsnap"
    eng.snapshot(path)
    tail_uninterrupted, _ = _continue_decode(eng, state, 5)

    eng2 = make_paged_engine(batch=2)
    eng2.restore(path)
    tail_restored, _ = _continue_decode(eng2, state, 5)
    np.testing.assert_array_equal(tail_uninterrupted, tail_restored)


def test_snapshot_sampling_path_mismatch_rejected(tmp_path, monkeypatch):
    """A snapshot taken on the device sampling path names it in the
    meta; an engine pinned to the host path refuses the restore with
    SnapshotMismatch instead of silently switching coin streams."""
    eng = make_paged_engine(batch=2)
    assert eng.sampling_path == "device"
    _sampled_decode(eng, 2)
    path = tmp_path / "dev.dlsnap"
    eng.snapshot(path)

    monkeypatch.setenv("DLLAMA_SAMPLING_PATH", "host")
    eng2 = make_paged_engine(batch=2)
    assert eng2.sampling_path == "host"
    with pytest.raises(snapfmt.SnapshotMismatch, match="sampling_path"):
        eng2.restore(path)

    monkeypatch.setenv("DLLAMA_SAMPLING_PATH", "device")
    eng3 = make_paged_engine(batch=2)
    eng3.restore(path)  # matching path restores fine


def test_handoff_record_carries_dev_key_and_rejects_mismatch(monkeypatch):
    """DLREQ01 hand-off records export the device RNG key and the
    engine's sampling-path flag; an importer on a different sampling
    path refuses the record before touching any state."""
    monkeypatch.delenv("DLLAMA_SAMPLING_PATH", raising=False)
    sa = SlotScheduler(make_paged_engine(batch=2), prefill_chunk=4,
                       max_wait_ms=20.0, decode_burst=4)
    try:
        with injected("engine.device_step=delay:0.05"):
            t = sa.submit(list(PROMPTS[0]), 30, temperature=0.7)
            it = t.tokens()
            for _ in range(4):
                next(it)
            records = sa.handoff_export_all()
        list(it)
    finally:
        sa.close()
    assert set(records) == {t.rid}
    meta, arrays = snapfmt.loads_request(records[t.rid])
    assert meta["extra"]["sampling_path"] == "device"
    assert "rng_dev_key" in arrays  # the sampled chunk seeded the chain

    monkeypatch.setenv("DLLAMA_SAMPLING_PATH", "host")
    sb = SlotScheduler(make_paged_engine(batch=2), prefill_chunk=4,
                       max_wait_ms=20.0)
    try:
        with pytest.raises(snapfmt.SnapshotMismatch, match="sampling_path"):
            sb.import_request(records[t.rid])
    finally:
        sb.close()

    monkeypatch.setenv("DLLAMA_SAMPLING_PATH", "device")
    sc = SlotScheduler(make_paged_engine(batch=2), prefill_chunk=4,
                       max_wait_ms=20.0)
    try:
        t2, extra = sc.import_request(records[t.rid])
        assert extra["sampling_path"] == "device"
        resumed = list(t2.tokens())
        assert len(meta["extra"]["completion"]) + len(resumed) == 30
    finally:
        sc.close()
