"""Blocked (flash-style) prefill attention ≡ the one-shot path.

The one-shot path materializes the full (B, Hkv, G, T, S) f32 score tensor
— the long-context HBM wall (VERDICT r01 weak #5); the blocked path scans
KV chunks with an online softmax and must be numerically equivalent."""

import jax
import jax.numpy as jnp
import numpy as np

from dllama_tpu.ops.attention import (blocked_gqa_attention, gqa_attention,
                                      update_kv_cache_at)


def _setup(b=1, hq=4, hkv=2, s=256, t=8, dh=16, pos=64, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, hq, t, dh).astype(np.float32))
    k = jnp.asarray(rng.randn(b, hkv, s, dh).astype(np.float32))
    v = jnp.asarray(rng.randn(b, hkv, s, dh).astype(np.float32))
    return q, k, v, jnp.int32(pos)


def test_blocked_matches_oneshot_mid_sequence():
    q, k, v, pos = _setup()
    ref = gqa_attention(q, k, v, pos, 8)
    out = blocked_gqa_attention(q, k, v, pos, 8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_blocked_matches_oneshot_from_zero():
    q, k, v, _ = _setup(t=16, s=512, pos=0)
    ref = gqa_attention(q, k, v, jnp.int32(0), 16)
    out = blocked_gqa_attention(q, k, v, jnp.int32(0), 16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_blocked_ragged_chunking():
    # s=96 falls through the divisor ladder to a single 96-wide chunk
    q, k, v, pos = _setup(s=96, pos=10, t=4)
    ref = gqa_attention(q, k, v, pos, 4)
    out = blocked_gqa_attention(q, k, v, pos, 4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_long_prefill_4k_dispatches_blocked():
    """A 4k-token prefill runs through gqa_attention's auto dispatch (the
    score tensor would be g·t·s = 2·4096·4096 = 32M > threshold) and
    matches the explicit one-shot computation on a spot block."""
    b, hq, hkv, dh, s = 1, 4, 2, 16, 4096
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, hq, s, dh).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(b, hkv, s, dh).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(b, hkv, s, dh).astype(np.float32) * 0.3)
    out = jax.jit(gqa_attention, static_argnums=(4,))(q, k, v, jnp.int32(0), s)
    assert out.shape == (b, hq, s, dh)
    assert np.all(np.isfinite(np.asarray(out)))
    # spot-check the first 32 queries against the one-shot path on a
    # truncated cache (those queries only see keys < 32... actually ≤ 31)
    ref = gqa_attention(q[:, :, :32], k[:, :, :128], v[:, :, :128], jnp.int32(0), 32)
    np.testing.assert_allclose(np.asarray(out[:, :, :32]), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_decode_step_still_oneshot_consistent():
    """T=1 decode keeps the one-shot path; blocked must agree anyway."""
    q, k, v, pos = _setup(t=1, pos=100)
    ref = gqa_attention(q, k, v, pos, 1)
    out = blocked_gqa_attention(q, k, v, pos, 1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_update_then_attend_roundtrip():
    """update_kv_cache_at + attention sees exactly the written keys: the
    stacked-cache layer write lands in the right (layer, pos) window."""
    L, b, hkv, s, dh = 3, 1, 2, 64, 8
    kc = jnp.zeros((L, b, hkv, s, dh))
    vc = jnp.zeros((L, b, hkv, s, dh))
    rng = np.random.RandomState(2)
    kn = jnp.asarray(rng.randn(b, hkv, 4, dh).astype(np.float32))
    vn = jnp.asarray(rng.randn(b, hkv, 4, dh).astype(np.float32))
    kc, vc = update_kv_cache_at(kc, vc, kn, vn, jnp.int32(1), jnp.int32(0))
    # untouched layers stay zero; the written layer holds kn/vn at pos 0
    assert float(jnp.abs(kc[0]).sum()) == 0.0 and float(jnp.abs(kc[2]).sum()) == 0.0
    np.testing.assert_array_equal(np.asarray(kc[1, :, :, :4]), np.asarray(kn))
    q = jnp.asarray(rng.randn(b, 4, 4, dh).astype(np.float32))
    out1 = gqa_attention(q, kc[1], vc[1], jnp.int32(0), 4)
    out2 = blocked_gqa_attention(q, kc[1], vc[1], jnp.int32(0), 4)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               rtol=1e-5, atol=1e-5)


def test_decode_blocked_matches_one_shot(monkeypatch):
    """The length-aware decode path (while_loop over live KV blocks) must
    equal full-cache one-shot attention at every position class."""
    from dllama_tpu.ops import attention
    from dllama_tpu.ops.attention import decode_gqa_attention

    r = np.random.RandomState(0)
    b, hq, hkv, s, dh = 1, 4, 2, 8192, 8
    q = jnp.asarray(r.randn(b, hq, 1, dh), jnp.float32)
    k = jnp.asarray(r.randn(b, hkv, s, dh), jnp.float32)
    v = jnp.asarray(r.randn(b, hkv, s, dh), jnp.float32)
    fn = jax.jit(decode_gqa_attention)
    for pos in (0, 1, 1023, 1024, 5000, s - 1):
        got = fn(q, k, v, jnp.int32(pos))
        # the reference must be the genuine one-shot full-cache path, not a
        # re-dispatch into the blocked implementation
        monkeypatch.setattr(attention, "_DECODE_BLOCKED_MIN_S", 1 << 30)
        ref = attention.gqa_attention(q, k, v, jnp.int32(pos), 1)
        monkeypatch.undo()
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_gqa_dispatches_decode_blocked_for_long_cache():
    from dllama_tpu.ops import attention

    r = np.random.RandomState(1)
    q = jnp.asarray(r.randn(1, 4, 1, 8), jnp.float32)
    k = jnp.asarray(r.randn(1, 2, 4096, 8), jnp.float32)
    v = jnp.asarray(r.randn(1, 2, 4096, 8), jnp.float32)
    got = attention.gqa_attention(q, k, v, jnp.int32(77), 1)
    ref = attention.decode_gqa_attention(q, k, v, jnp.int32(77))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_stacked_decode_blocked_matches_per_layer():
    """gqa_attention_at over a long stacked cache (blocks sliced straight
    from the 5-D buffer — no layer-slab materialization) must equal the
    per-layer length-aware path on that layer's slice."""
    from dllama_tpu.ops import attention

    r = np.random.RandomState(3)
    L, b, hq, hkv, s, dh = 3, 1, 4, 2, 4096, 8
    q = jnp.asarray(r.randn(b, hq, 1, dh), jnp.float32)
    ck = jnp.asarray(r.randn(L, b, hkv, s, dh), jnp.float32)
    cv = jnp.asarray(r.randn(L, b, hkv, s, dh), jnp.float32)
    for layer in range(L):
        for pos in (0, 1023, 1024, s - 1):
            got = attention.gqa_attention_at(
                q, ck, cv, jnp.int32(layer), jnp.int32(pos), 1)
            ref = attention.decode_gqa_attention(
                q, ck[layer], cv[layer], jnp.int32(pos))
            np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
