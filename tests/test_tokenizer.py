"""Tokenizer / chat-template / EOS-detector tests.

Ports the reference test strategy from `src/tokenizer-test.cpp:14-176`:
template type detection from real Jinja fragments, and the EosDetector
streaming state machine (partial-match holdback, padding variants, delta
extraction).  BPE encode is validated on a constructed sentencepiece-style
vocab with byte fallback."""

import pytest

from dllama_tpu.io.tfile import TokenizerData
from dllama_tpu.sampling import Sampler, xorshift_f32
from dllama_tpu.tokenizer.bpe import Tokenizer
from dllama_tpu.tokenizer.chat import (ChatItem, ChatTemplate, TokenizerChatStops,
                                       detect_template_type)
from dllama_tpu.tokenizer.eos import EOS, MAYBE_EOS, NOT_EOS, EosDetector

import numpy as np


def make_tokenizer():
    # sentencepiece-like vocab: specials, byte pieces at ids 3..258, then words
    vocab = [b"<unk>", b"<s>", b"</s>"]
    vocab += [f"<0x{i:02X}>".encode() for i in range(256)]
    words = [b" ", b"h", b"e", b"l", b"o", b"he", b"ll", b"hell", b"hello", b" hello",
             b"w", b"r", b"d", b"wo", b"wor", b"worl", b"world", b" world"]
    scores = [0.0] * len(vocab)
    # longer merges get higher scores so greedy BPE prefers them
    for wpiece in words:
        vocab.append(wpiece)
        scores.append(float(len(wpiece)))
    data = TokenizerData(vocab=vocab, scores=scores, bos_id=1, eos_id=2,
                         chat_eos_id=2, chat_template=None, chat_stop=None)
    return Tokenizer(data)


def test_encode_merges_to_words():
    t = make_tokenizer()
    ids = t.encode("hello world", add_bos=True)
    assert ids[0] == t.bos_id
    pieces = [t.vocab[i] for i in ids[1:]]
    # dummy prefix " " merges with "hello"; " world" merges fully
    assert b"".join(pieces) == b" hello world"
    assert pieces == [b" hello", b" world"]


def test_encode_byte_fallback():
    t = make_tokenizer()
    ids = t.encode("h\x07", add_bos=False)
    # \x07 is not in vocab → byte fallback id = 7 + 3 (tokenizer.cpp:250-253)
    assert ids[-1] == 0x07 + 3


def test_encode_utf8_multibyte_fallback():
    t = make_tokenizer()
    ids = t.encode("é", add_bos=False)  # 0xC3 0xA9, not in vocab
    assert ids[-2:] == [0xC3 + 3, 0xA9 + 3]


def test_encode_empty_adds_only_bos():
    t = make_tokenizer()
    assert t.encode("", add_bos=True) == [1]
    assert t.encode("", add_bos=False) == []


def test_decode_strips_space_after_bos_and_bytes():
    t = make_tokenizer()
    ids = t.encode("hello", add_bos=True)
    assert t.decode(ids) == "hello"
    # byte piece decode
    assert t.decode_piece(0, 0x41 + 3) == b"A"


def test_encode_decode_roundtrip():
    t = make_tokenizer()
    for text in ["hello world", "hello", "held", "wow"]:
        ids = t.encode(text, add_bos=True)
        assert t.decode(ids) == text


# --- chat templates (tokenizer-test.cpp:14-56 spirit) ---

LLAMA3_JINJA = "{% set loop_messages = messages %}<|start_header_id|>..."
ZEPHYR_JINJA = "{% for message in messages %}<|user|>..."
CHATML_JINJA = "{% for message in messages %}<|im_start|>..."


def test_template_detection():
    assert detect_template_type(LLAMA3_JINJA) == "llama3"
    assert detect_template_type(ZEPHYR_JINJA) == "zephyr"
    assert detect_template_type(CHATML_JINJA) == "chatml"
    with pytest.raises(ValueError):
        detect_template_type("{{ bos_token }}{% raw %}nope{% endraw %}")


def test_llama3_render():
    ct = ChatTemplate(LLAMA3_JINJA, "<|eot_id|>")
    out = ct.generate([ChatItem("system", "sys"), ChatItem("user", "hi")], True)
    assert out == ("<|start_header_id|>system<|end_header_id|>\n\nsys<|eot_id|>"
                   "<|start_header_id|>user<|end_header_id|>\n\nhi<|eot_id|>"
                   "<|start_header_id|>assistant<|end_header_id|>\n\n")


def test_chatml_render():
    ct = ChatTemplate(CHATML_JINJA, "<|im_end|>")
    out = ct.generate([ChatItem("user", "hi")], True)
    assert out == "<|im_start|>user\nhi<|im_end|>\n<|im_start|>assistant\n"


def test_zephyr_render():
    ct = ChatTemplate(ZEPHYR_JINJA, "</s>")
    out = ct.generate([ChatItem("user", "hi")], False)
    assert out == "<|user|>\nhi</s>\n"


def test_chat_stops():
    t = make_tokenizer()
    t.chat_eos_id = 2
    stops = TokenizerChatStops(t)
    assert stops.stops == ["</s>"]
    t.chat_stop = "<|im_end|>"
    stops = TokenizerChatStops(t)
    assert stops.stops == ["</s>", "<|im_end|>"] and stops.max_stop_length == 10


# --- EosDetector (tokenizer-test.cpp:58-176 spirit) ---

def test_eos_token_id_is_hard_stop():
    d = EosDetector(2, ["<eos>"])
    assert d.append(2, "<eos>") == EOS
    assert d.get_delta() is None


def test_eos_string_across_pieces():
    d = EosDetector(-1, ["<eos>"])
    assert d.append(5, "<e") == MAYBE_EOS
    assert d.append(6, "os>") == EOS
    assert d.get_delta() is None


def test_eos_with_left_padding():
    d = EosDetector(-1, ["<eos>"], padding_left=2)
    assert d.append(5, "x<eos>") == EOS
    assert d.get_delta() == "x"


def test_eos_with_right_padding():
    d = EosDetector(-1, ["<eos>"], padding_right=2)
    assert d.append(5, "<eos>y") == EOS
    assert d.get_delta() is None


def test_not_eos_flushes_text():
    d = EosDetector(-1, ["<eos>"])
    assert d.append(5, "hello") == NOT_EOS
    assert d.get_delta() == "hello"
    d.clear()
    assert d.append(6, "<e") == MAYBE_EOS
    assert d.append(7, "xx") == NOT_EOS
    assert d.get_delta() == "<exx"


def test_maybe_then_overflow_is_not_eos():
    d = EosDetector(-1, ["<eos>"])
    assert d.append(5, "<eo") == MAYBE_EOS
    assert d.append(6, "zzzzzz") == NOT_EOS


# --- Sampler ---

def test_sampler_greedy():
    s = Sampler(5, 0.0, 0.9, 1)
    assert s.sample(np.array([0.1, 3.0, 0.2, 0.0, -1.0])) == 1


def test_sampler_temperature_deterministic_seed():
    logits = np.linspace(0, 2, 32).astype(np.float32)
    a = Sampler(32, 0.8, 0.0, 12345).sample(logits.copy())
    b = Sampler(32, 0.8, 0.0, 12345).sample(logits.copy())
    assert a == b


def test_sampler_topp_prunes_tail():
    # one dominant token with topp=0.5 → always chosen regardless of coin
    logits = np.full(16, -10.0, dtype=np.float32)
    logits[3] = 10.0
    for seed in range(5):
        assert Sampler(16, 1.0, 0.5, seed).sample(logits.copy()) == 3


def test_xorshift_range():
    state = 12345
    for _ in range(100):
        state, v = xorshift_f32(state)
        assert 0.0 <= v < 1.0


# ---------------------------------------------------------------------------
# Merge-engine equivalence: heap (Python) ≡ native (C++) ≡ reference rescan
# ---------------------------------------------------------------------------

def _reference_rescan_merge(tok, tokens):
    """The reference's O(n²) loop (tokenizer.cpp:258-287), kept verbatim as
    the behavioral oracle for the fast merge engines."""
    tokens = list(tokens)
    while True:
        best_score, best_id, best_idx = -1e10, -1, -1
        for k in range(len(tokens) - 1):
            merged = tok.vocab[tokens[k]] + tok.vocab[tokens[k + 1]]
            mid = tok._index.get(merged, -1)
            if mid != -1 and tok.scores[mid] > best_score:
                best_score, best_id, best_idx = tok.scores[mid], mid, k
        if best_idx == -1:
            return tokens
        tokens[best_idx: best_idx + 2] = [best_id]


@pytest.mark.parametrize("use_native", [False, True])
def test_merge_engines_match_reference_oracle(use_native, monkeypatch):
    from dllama_tpu import native

    if use_native and native._bpe_lib() is None:
        pytest.skip("libbpe.so not built")
    if not use_native:
        monkeypatch.setattr(native, "bpe_merge", lambda *_: None)
    tok = make_tokenizer()
    rng = np.random.RandomState(0)
    texts = ["hello world", "hhheeellllllooo", "wwwoorrlld hello",
             "", "h", "x" * 50]
    texts += ["".join(rng.choice(list("helowrd x")) for _ in range(n))
              for n in (7, 31, 100, 257)]
    for text in texts:
        raw = text.encode()
        base = [tok.lookup(bytes([b])) if tok.lookup(bytes([b])) != -1 else b + 3
                for b in raw]
        assert tok._merge(list(base)) == _reference_rescan_merge(tok, base), text


def test_long_prompt_encode_is_fast():
    """The quadratic rescan made 100k-char prompts unencodable; the merge
    engines must handle them in seconds (ring-prefill's enabling half)."""
    import time

    tok = make_tokenizer()
    text = "hello world " * 10000  # 120k chars
    t0 = time.time()
    ids = tok.encode(text)
    assert time.time() - t0 < 20.0
    assert tok.decode(ids).strip() == text.strip()


def test_drain_generation_split_codepoint_renders_per_fragment():
    """drain_generation decodes per piece (the EosDetector's stop
    arithmetic is per-piece character positions, so an incremental
    decoder that carries bytes across pieces would corrupt eos/stop
    cuts): a codepoint split across byte-fallback tokens renders as one
    U+FFFD per fragment.  The batched completions stream reassembles
    (buffer-based stop logic) — see stream.py for the tradeoff."""
    from dllama_tpu.runtime.stream import drain_generation
    from dllama_tpu.tokenizer.eos import EosDetector

    class StubTok:
        bos_id = 0

        def decode_piece(self, prev, t):
            #                    '€' = e2 82 ac, split across two tokens
            return {1: b"\xe2\x82", 2: b"\xac", 3: b"!"}[t]

    class StubEngine:
        pos = 10

    deltas = []
    stream = iter([(1, None), (2, None), (3, None)])
    reply, n, eos = drain_generation(
        StubEngine(), StubTok(), EosDetector(99, []), stream,
        n_prompt=0, prompt_end=10, on_delta=deltas.append)
    assert reply == "\ufffd\ufffd!"
    assert n == 3 and not eos
